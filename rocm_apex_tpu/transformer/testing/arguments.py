"""Megatron-style argument system.

Rebuild of the reference's de-facto config schema
(reference: apex/transformer/testing/arguments.py, 806 LoC — the full
Megatron argparser grouped as model/regularization/training/
initialization/learning-rate/checkpointing/mixed-precision/distributed/
validation/data groups, with `parse_args(extra_args_provider,
defaults, ignore_unknown_args)` and post-parse consistency checks).

This carries the same group structure and the flags the framework
consumes; CUDA-only knobs keep their names where downstream scripts
pass them (accepted, unused) and are marked so. Consistency checks
mirror the reference's (world-size divisibility, fp16/bf16 exclusivity,
virtual-pipeline constraints).
"""

import argparse
import os

__all__ = ["parse_args"]


def parse_args(extra_args_provider=None, defaults=None,
               ignore_unknown_args=False, args=None):
    parser = argparse.ArgumentParser(
        description="rocm_apex_tpu Arguments", allow_abbrev=False
    )
    _add_model_config_args(parser)
    _add_regularization_args(parser)
    _add_training_args(parser)
    _add_initialization_args(parser)
    _add_learning_rate_args(parser)
    _add_checkpointing_args(parser)
    _add_mixed_precision_args(parser)
    _add_distributed_args(parser)
    _add_validation_args(parser)
    _add_data_args(parser)
    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)

    if defaults:
        for k, v in defaults.items():
            if getattr(parsed, k, None) is None:
                setattr(parsed, k, v)

    # consistency checks (reference arguments.py post-parse validation)
    import jax

    parsed.world_size = int(
        os.environ.get("WORLD_SIZE", jax.device_count())
    )
    model_size = (
        parsed.tensor_model_parallel_size * parsed.pipeline_model_parallel_size
    )
    if parsed.world_size % model_size != 0:
        raise ValueError(
            f"world size ({parsed.world_size}) is not divisible by tensor "
            f"({parsed.tensor_model_parallel_size}) x pipeline "
            f"({parsed.pipeline_model_parallel_size}) parallel sizes"
        )
    parsed.data_parallel_size = parsed.world_size // model_size
    if parsed.fp16 and parsed.bf16:
        raise ValueError("cannot specify both fp16 and bf16")
    if parsed.virtual_pipeline_model_parallel_size is not None:
        if parsed.pipeline_model_parallel_size <= 2:
            raise ValueError(
                "pipeline-model-parallel size should be greater than 2 "
                "with interleaved schedule"
            )
        if (
            parsed.num_layers
            % (
                parsed.virtual_pipeline_model_parallel_size
                * parsed.pipeline_model_parallel_size
            )
            != 0
        ):
            raise ValueError(
                "number of layers is not divisible by number of model chunks"
            )
    if parsed.ffn_hidden_size is None:
        parsed.ffn_hidden_size = 4 * parsed.hidden_size
    if parsed.kv_channels is None:
        assert parsed.hidden_size % parsed.num_attention_heads == 0
        parsed.kv_channels = parsed.hidden_size // parsed.num_attention_heads
    return parsed


def _add_model_config_args(p):
    g = p.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=None)
    g.add_argument("--hidden-size", type=int, default=None)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--num-attention-heads", type=int, default=None)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=None)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    g.add_argument("--apply-residual-connection-post-layernorm",
                   action="store_true")
    g.add_argument("--openai-gelu", action="store_true")
    g.add_argument("--onnx-safe", action="store_true")


def _add_regularization_args(p):
    g = p.add_argument_group("regularization")
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--sgd-momentum", type=float, default=0.9)


def _add_training_args(p):
    g = p.add_argument_group("training")
    g.add_argument("--micro-batch-size", type=int, default=None)
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", default=None)
    g.add_argument("--checkpoint-activations", action="store_true")
    g.add_argument("--distribute-checkpointed-activations",
                   action="store_true")
    g.add_argument("--train-iters", type=int, default=None)
    g.add_argument("--train-samples", type=int, default=None)
    g.add_argument("--log-interval", type=int, default=100)
    g.add_argument("--exit-interval", type=int, default=None)
    g.add_argument("--tensorboard-dir", type=str, default=None)
    g.add_argument("--optimizer", type=str, default="adam",
                   choices=["adam", "sgd", "lamb"])
    g.add_argument("--use-cpu-initialization", action="store_true",
                   help="accepted for parity; initialization is functional")


def _add_initialization_args(p):
    g = p.add_argument_group("initialization")
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--init-method-std", type=float, default=0.02)
    g.add_argument("--init-method-xavier-uniform", action="store_true")


def _add_learning_rate_args(p):
    g = p.add_argument_group("learning rate")
    g.add_argument("--lr", type=float, default=None)
    g.add_argument("--lr-decay-style", type=str, default="linear",
                   choices=["constant", "linear", "cosine"])
    g.add_argument("--lr-decay-iters", type=int, default=None)
    g.add_argument("--lr-decay-samples", type=int, default=None)
    g.add_argument("--lr-warmup-fraction", type=float, default=None)
    g.add_argument("--lr-warmup-iters", type=int, default=0)
    g.add_argument("--lr-warmup-samples", type=int, default=0)
    g.add_argument("--min-lr", type=float, default=0.0)
    g.add_argument("--override-lr-scheduler", action="store_true")
    g.add_argument("--use-checkpoint-lr-scheduler", action="store_true")


def _add_checkpointing_args(p):
    g = p.add_argument_group("checkpointing")
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--no-save-optim", action="store_true")
    g.add_argument("--no-save-rng", action="store_true")
    g.add_argument("--load", type=str, default=None)
    g.add_argument("--no-load-optim", action="store_true")
    g.add_argument("--no-load-rng", action="store_true")
    g.add_argument("--finetune", action="store_true")


def _add_mixed_precision_args(p):
    g = p.add_argument_group("mixed precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2**32)
    g.add_argument("--min-loss-scale", type=float, default=1.0)
    g.add_argument("--loss-scale-window", type=float, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--fp32-residual-connection", action="store_true")
    g.add_argument("--no-query-key-layer-scaling", action="store_false",
                   dest="apply_query_key_layer_scaling")
    g.add_argument("--attention-softmax-in-fp32", action="store_true")
    g.add_argument("--accumulate-allreduce-grads-in-fp32",
                   action="store_true")
    g.add_argument("--fp16-lm-cross-entropy", action="store_true")


def _add_distributed_args(p):
    g = p.add_argument_group("distributed")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                   default=None)
    g.add_argument("--distributed-backend", default="xla",
                   choices=["xla", "nccl", "gloo"],
                   help="accepted for parity; collectives are XLA's")
    g.add_argument("--DDP-impl", default="local",
                   choices=["local", "torch"],
                   help="accepted for parity")
    g.add_argument("--local_rank", type=int, default=None)
    g.add_argument("--lazy-mpu-init", type=bool, default=None)
    g.add_argument("--use-ring-exchange-p2p", action="store_true")
    g.add_argument("--scatter-gather-tensors-in-pipeline",
                   action="store_true")


def _add_validation_args(p):
    g = p.add_argument_group("validation")
    g.add_argument("--eval-iters", type=int, default=100)
    g.add_argument("--eval-interval", type=int, default=1000)


def _add_data_args(p):
    g = p.add_argument_group("data")
    g.add_argument("--data-path", nargs="*", default=None)
    g.add_argument("--split", type=str, default="969, 30, 1")
    g.add_argument("--vocab-file", type=str, default=None)
    g.add_argument("--merge-file", type=str, default=None)
    g.add_argument("--seq-length", type=int, default=None)
    g.add_argument("--encoder-seq-length", type=int, default=None)
    g.add_argument("--decoder-seq-length", type=int, default=None)
    g.add_argument("--num-workers", type=int, default=2)
    g.add_argument("--reset-position-ids", action="store_true")
    g.add_argument("--reset-attention-mask", action="store_true")
    g.add_argument("--eod-mask-loss", action="store_true")
