"""Test/bring-up utilities: Megatron-style args, globals, toy models.

Reference: apex/transformer/testing/ — arguments.py (806 LoC argparse =
the de-facto Megatron config schema), global_vars.py (singleton
args/timers), commons.py (initialize_distributed, toy MyModel).
"""

from rocm_apex_tpu.transformer.testing.arguments import parse_args  # noqa: F401
from rocm_apex_tpu.transformer.testing.commons import (  # noqa: F401
    MyLayer,
    MyModel,
    initialize_mesh,
)
from rocm_apex_tpu.transformer.testing.global_vars import (  # noqa: F401
    get_args,
    get_timers,
    set_global_variables,
)

__all__ = [
    "parse_args",
    "get_args",
    "get_timers",
    "set_global_variables",
    "initialize_mesh",
    "MyLayer",
    "MyModel",
]
