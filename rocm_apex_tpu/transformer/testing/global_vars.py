"""Global args/timers singletons.

Reference: apex/transformer/testing/global_vars.py:1-270 — `get_args`,
`get_timers`, `set_global_variables`, each guarded by
is-initialized assertions.
"""

from typing import Optional

from rocm_apex_tpu.transformer._timers import Timers

__all__ = ["get_args", "get_timers", "set_global_variables"]

_GLOBAL_ARGS = None
_GLOBAL_TIMERS = None


def _ensure(var, name):
    if var is None:
        raise AssertionError(f"{name} is not initialized.")
    return var


def get_args():
    return _ensure(_GLOBAL_ARGS, "args")


def get_timers() -> Timers:
    return _ensure(_GLOBAL_TIMERS, "timers")


def set_global_variables(
    extra_args_provider=None,
    args_defaults: Optional[dict] = None,
    ignore_unknown_args: bool = False,
    args=None,
):
    """Parse args + build timers (reference global_vars.py:87-270)."""
    global _GLOBAL_ARGS, _GLOBAL_TIMERS
    from rocm_apex_tpu.transformer.testing.arguments import parse_args

    if _GLOBAL_ARGS is not None:
        raise AssertionError("args is already initialized.")
    _GLOBAL_ARGS = parse_args(
        extra_args_provider=extra_args_provider,
        defaults=args_defaults,
        ignore_unknown_args=ignore_unknown_args,
        args=args,
    )
    _GLOBAL_TIMERS = Timers()
    return _GLOBAL_ARGS


def _destroy_global_vars():
    global _GLOBAL_ARGS, _GLOBAL_TIMERS
    _GLOBAL_ARGS = None
    _GLOBAL_TIMERS = None
