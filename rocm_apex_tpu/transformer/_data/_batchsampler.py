"""DP-sharded pretraining batch samplers.

Rebuild of the reference samplers
(reference: apex/transformer/_data/_batchsampler.py —
`MegatronPretrainingSampler:37` sequential, `MegatronPretrainingRandomSampler`
epoch-seeded shuffled buckets). Framework-agnostic index iterators:
each `__iter__` yields this data-parallel rank's local minibatch of
dataset indices, resumable via `consumed_samples`. torch's seeded
`randperm` becomes numpy's (same role: deterministic per epoch).
"""

import numpy as np

__all__ = ["MegatronPretrainingSampler", "MegatronPretrainingRandomSampler"]


class MegatronPretrainingSampler:
    """Sequential DP-sharded sampler (reference :37-99)."""

    def __init__(
        self,
        total_samples: int,
        consumed_samples: int,
        local_minibatch_size: int,
        data_parallel_rank: int,
        data_parallel_size: int,
        drop_last: bool = True,
    ):
        if total_samples <= 0:
            raise RuntimeError(f"no sample to consume: {total_samples}")
        if consumed_samples >= total_samples:
            raise RuntimeError(
                f"no samples left to consume: {consumed_samples}, "
                f"{total_samples}"
            )
        if local_minibatch_size <= 0:
            raise RuntimeError(
                "local minibatch size must be greater than 0: "
                f"{local_minibatch_size}"
            )
        if data_parallel_size <= 0:
            raise RuntimeError(
                f"data parallel size must be greater than 0: {data_parallel_size}"
            )
        if data_parallel_rank >= data_parallel_size:
            raise RuntimeError(
                "data_parallel_rank should be smaller than data size: "
                f"{data_parallel_rank}, {data_parallel_size}"
            )
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.drop_last = drop_last

    def __len__(self):
        return self.total_samples

    def get_start_end_idx(self):
        start = self.data_parallel_rank * self.local_minibatch_size
        return start, start + self.local_minibatch_size

    def __iter__(self):
        # Deliberate deviation: the reference accumulates only
        # local_minibatch_size indices before rank-slicing
        # (_batchsampler.py:86-99), which yields empty batches for every
        # rank > 0; upstream Megatron accumulates batch_size *
        # data_parallel_size. We accumulate lms * dp so each rank gets
        # its disjoint window.
        batch = []
        full = self.local_minibatch_size * self.data_parallel_size
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == full:
                start, end = self.get_start_end_idx()
                yield batch[start:end]
                batch = []
        if batch and not self.drop_last:
            start, end = self.get_start_end_idx()
            yield batch[start:end]


class MegatronPretrainingRandomSampler:
    """Shuffled DP-sharded sampler; epoch-seeded permutation over this
    rank's bucket (reference :103-180)."""

    def __init__(
        self,
        total_samples: int,
        consumed_samples: int,
        local_minibatch_size: int,
        data_parallel_rank: int,
        data_parallel_size: int,
    ):
        if total_samples <= 0:
            raise ValueError(f"no sample to consume: {total_samples}")
        if local_minibatch_size <= 0:
            raise ValueError(f"Invalid local_minibatch_size: {local_minibatch_size}")
        if data_parallel_size <= 0:
            raise ValueError(f"Invalid data_parallel_size: {data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError(
                "data_parallel_rank should be smaller than data parallel "
                f"size: {data_parallel_rank} < {data_parallel_size}"
            )
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size
        )
        self.last_batch_size = (
            total_samples % self.local_minibatch_times_data_parallel_size
        )

    def __len__(self):
        return self.total_samples

    def __iter__(self):
        active = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active
        current_epoch_samples = self.consumed_samples % active
        bucket_size = (
            self.total_samples // self.local_minibatch_times_data_parallel_size
        ) * self.local_minibatch_size
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        rng = np.random.default_rng(self.epoch)
        random_idx = rng.permutation(bucket_size).tolist()
        idx_range = [start_idx + x for x in random_idx[bucket_offset:]]

        batch = []
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.local_minibatch_size:
                self.consumed_samples += (
                    self.local_minibatch_times_data_parallel_size
                )
                yield batch
                batch = []
