"""Megatron pretraining batch samplers.

Reference: apex/transformer/_data/_batchsampler.py:37-180.
"""

from rocm_apex_tpu.transformer._data._batchsampler import (  # noqa: F401
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)

__all__ = ["MegatronPretrainingSampler", "MegatronPretrainingRandomSampler"]
