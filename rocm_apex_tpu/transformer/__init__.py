"""Megatron-style model parallelism for TPU meshes.

Mirrors the reference `apex.transformer` package layout
(reference: apex/transformer/__init__.py): `parallel_state` (the "mpu"),
`tensor_parallel`, `pipeline_parallel`, `functional` (fused softmax), and
`amp` (model-parallel-aware grad scaler).
"""

from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.transformer import tensor_parallel

__all__ = ["parallel_state", "tensor_parallel"]
