"""Expert parallelism: Switch-style MoE over the ``expert`` mesh axis.

Capability beyond the reference (which has no MoE/expert-parallel code;
SURVEY.md §2.5 notes the absent strategies) — the ``expert`` axis the
mesh design reserves (parallel_state.EXPERT_AXIS) put to work:

* top-1 (switch) gating with capacity-bounded dispatch;
* token exchange via TWO `lax.all_to_all`s (dispatch + return) — the
  collective the reference would have spelled as grouped NCCL
  all-to-all;
* each rank hosts ``num_experts / axis_size`` expert FFNs and runs them
  on the tokens routed to it from every rank.

Everything is dense einsum against one-hot dispatch tensors (the
Mesh-TensorFlow/Switch formulation), so the whole layer is jit/grad
transparent and the router is differentiable through the gate
probabilities. Tokens overflowing an expert's capacity are dropped
(standard switch behavior); the auxiliary load-balancing loss
(`load_balancing_loss`) is returned for the trainer to add.
"""

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.utils.compat import axis_size

__all__ = ["SwitchMLP", "switch_route", "load_balancing_loss"]


def switch_route(gate_logits: jnp.ndarray, capacity: int):
    """Top-1 routing -> (dispatch (T, E, C) bool, combine (T, E, C) f32).

    Tokens beyond `capacity` per expert are dropped. combine = dispatch
    * gate probability (differentiable through the softmax).
    """
    T, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (T, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # (T, E), -1 elsewhere
    keep = (pos >= 0) & (pos < capacity)
    pos_c = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    dispatch = keep[..., None] & (
        jax.nn.one_hot(pos_c, capacity, dtype=jnp.bool_)
    )
    gate = jnp.max(probs * onehot, axis=-1)  # (T,) chosen prob
    combine = dispatch.astype(jnp.float32) * gate[:, None, None]
    return dispatch, combine, probs, onehot


def load_balancing_loss(probs: jnp.ndarray, onehot: jnp.ndarray) -> jnp.ndarray:
    """Switch aux loss: E * sum_e f_e * P_e (fraction routed x mean prob)."""
    E = probs.shape[-1]
    f = jnp.mean(onehot, axis=0)
    P = jnp.mean(probs, axis=0)
    return E * jnp.sum(f * P)


class SwitchMLP(nn.Module):
    """Expert-parallel switch FFN layer.

    ``num_experts`` total experts; inside `shard_map` with
    ``expert_axis`` bound each rank hosts ``num_experts / axis_size``
    of them and tokens travel by all_to_all. Without the axis bound the
    layer runs all experts locally (single-device fallback).

    Returns ``(y, aux_loss)``.
    """

    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    capacity_factor: float = 1.25
    expert_axis: str = parallel_state.EXPERT_AXIS
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        *batch, h = x.shape
        xt = x.reshape(-1, h)
        T = xt.shape[0]
        E = self.num_experts
        try:
            n = axis_size(self.expert_axis)
        except NameError:
            n = 1
        if E % n:
            raise ValueError(
                f"num_experts {E} not divisible by {self.expert_axis} "
                f"axis size {n}"
            )
        e_local = E // n
        capacity = max(1, int(np.ceil(T * self.capacity_factor / E)))

        gate_logits = nn.Dense(
            E, use_bias=False, dtype=jnp.float32,
            param_dtype=self.param_dtype, name="router",
        )(xt)
        dispatch, combine, probs, onehot = switch_route(gate_logits, capacity)
        aux = load_balancing_loss(probs, onehot)

        # (T, E, C) x (T, h) -> (E, C, h) expert queues
        xe = jnp.einsum(
            "tec,th->ech", dispatch.astype(self.dtype), xt.astype(self.dtype)
        )
        if n > 1:
            # to expert-owners: tiled all_to_all splits the expert axis
            # into rank blocks — rank r receives its (e_local, C, h)
            # queues from every rank, concatenated along the token dim:
            # (E, C, h) -> (e_local, n*C, h)
            xe = jax.lax.all_to_all(
                xe, self.expert_axis, split_axis=0, concat_axis=1,
                tiled=True,
            )
        else:
            xe = xe.reshape(e_local, capacity, h)

        # per-local-expert FFN (vmapped parameters: leading e_local axis)
        w1 = self.param(
            "wi", nn.initializers.lecun_normal(),
            (e_local, h, self.ffn_hidden_size), self.param_dtype,
        )
        w2 = self.param(
            "wo", nn.initializers.lecun_normal(),
            (e_local, self.ffn_hidden_size, h), self.param_dtype,
        )
        ye = jnp.einsum(
            "ekh,ehf->ekf", xe, w1.astype(self.dtype)
        )
        ye = nn.gelu(ye)
        ye = jnp.einsum(
            "ekf,efh->ekh", ye, w2.astype(self.dtype)
        )

        if n > 1:
            # exact inverse of the dispatch exchange:
            # (e_local, n*C, h) -> (E, C, h)
            ye = jax.lax.all_to_all(
                ye, self.expert_axis, split_axis=1, concat_axis=0,
                tiled=True,
            )
        else:
            ye = ye.reshape(E, capacity, h)

        y = jnp.einsum(
            "tec,ech->th", combine.astype(self.dtype), ye
        )
        return y.reshape(*batch, h), aux
