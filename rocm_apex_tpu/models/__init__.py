"""Reference model zoo: Megatron-style GPT and BERT, flax-native.

Rebuild of the reference's testing models
(reference: apex/transformer/testing/standalone_gpt.py (1504 LoC) and
standalone_bert.py), which exist so the TP/PP machinery can be validated
on a real transformer. Here they double as the framework's flagship
models: TP via the shard_map tensor-parallel layers, PP via uniform
`ParallelTransformerLayer` stacks fed to the pipeline schedules, DP via
the mesh data axis.
"""

from rocm_apex_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    ParallelAttention,
    ParallelMLP,
    ParallelTransformer,
    ParallelTransformerLayer,
    TransformerEmbedding,
    gpt_loss_fn,
)
from rocm_apex_tpu.models.bert import BertConfig, BertModel  # noqa: F401
from rocm_apex_tpu.models.dcgan import Discriminator, Generator  # noqa: F401
from rocm_apex_tpu.models.resnet import (  # noqa: F401
    BasicBlock,
    Bottleneck,
    FoldedConvBN,
    ResNet,
    resnet_tiny,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
)

__all__ = [
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "Generator",
    "Discriminator",
    "GPTConfig",
    "GPTModel",
    "ParallelMLP",
    "ParallelAttention",
    "ParallelTransformerLayer",
    "ParallelTransformer",
    "TransformerEmbedding",
    "gpt_loss_fn",
    "BertConfig",
    "BertModel",
]
