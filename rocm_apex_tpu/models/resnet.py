"""ResNet family, TPU-native (NHWC), with SyncBatchNorm option.

The reference's north-star example trains torchvision ResNet-50 under
amp + DDP (reference: examples/imagenet/main_amp.py; the L1 harness
runs b=128 RN50, tests/L1/common/run_test.sh:20-27). This is that model
as flax modules: NHWC layout (TPU conv-native; the reference reaches
the same layout via --channels-last), `nn.BatchNorm` by default or the
framework's cross-replica `SyncBatchNorm` when `sync_bn_axis` is set
(reference: apex.parallel.SyncBatchNorm + convert_syncbn_model).
"""

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from rocm_apex_tpu.parallel import SyncBatchNorm

__all__ = [
    "ResNet",
    "BasicBlock",
    "Bottleneck",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
]


def _norm(cfg_axis, dtype):
    if cfg_axis is not None:
        return functools.partial(
            SyncBatchNorm,
            momentum=0.1,
            axis_name=cfg_axis,
            channel_last=True,
            dtype=dtype,
        )
    return functools.partial(
        nn.BatchNorm, momentum=0.9, epsilon=1e-5, dtype=dtype
    )


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    norm: Any = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(
            self.filters, (3, 3), (self.strides, self.strides),
            padding=1, use_bias=False, dtype=self.dtype, name="conv1",
        )(x)
        y = self.norm(name="bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.filters, (3, 3), padding=1, use_bias=False,
            dtype=self.dtype, name="conv2",
        )(y)
        y = self.norm(name="bn2")(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), (self.strides, self.strides),
                use_bias=False, dtype=self.dtype, name="downsample_conv",
            )(residual)
            residual = self.norm(name="downsample_bn")(
                residual, use_running_average=not train
            )
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1
    norm: Any = None
    dtype: jnp.dtype = jnp.float32
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(
            self.filters, (1, 1), use_bias=False, dtype=self.dtype,
            name="conv1",
        )(x)
        y = self.norm(name="bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.filters, (3, 3), (self.strides, self.strides), padding=1,
            use_bias=False, dtype=self.dtype, name="conv2",
        )(y)
        y = self.norm(name="bn2")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.filters * self.expansion, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv3",
        )(y)
        y = self.norm(name="bn3")(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * self.expansion, (1, 1),
                (self.strides, self.strides), use_bias=False,
                dtype=self.dtype, name="downsample_conv",
            )(residual)
            residual = self.norm(name="downsample_bn")(
                residual, use_running_average=not train
            )
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """NHWC ResNet. `sync_bn_axis` switches BN to cross-replica stats.

    `fused=True` routes every stride-1 bottleneck block through the
    fused Pallas kernel chain (ops/fused_bottleneck.py: BN-apply
    prologues, conv-on-MXU, BN-stats epilogues, merged backward) — the
    reference's cudnn fused-bottleneck analogue (reference:
    apex/contrib/bottleneck/bottleneck.py:112). Stride-2 blocks and the
    stem keep the XLA path; SyncBatchNorm and BasicBlock nets ignore
    the flag.
    """

    stage_sizes: Sequence[int]
    block: Any = Bottleneck
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.float32
    sync_bn_axis: Optional[str] = None
    fused: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = _norm(self.sync_bn_axis, self.dtype)
        x = nn.Conv(
            self.num_filters, (7, 7), (2, 2), padding=3, use_bias=False,
            dtype=self.dtype, name="conv1",
        )(x)
        x = norm(name="bn1")(x, use_running_average=not train)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=((1, 1), (1, 1)))
        use_fused = (
            self.fused
            and self.block is Bottleneck
            and self.sync_bn_axis is None
        )
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                filters = self.num_filters * 2**i
                if use_fused and strides == 1:
                    from rocm_apex_tpu.contrib.bottleneck import (
                        FusedBottleneck,
                    )

                    x = FusedBottleneck(
                        in_channels=x.shape[-1],
                        bottleneck_channels=filters,
                        out_channels=filters * 4,
                        dtype=self.dtype,
                        name=f"layer{i + 1}_{j}",
                    )(x, train)
                    continue
                x = self.block(
                    filters,
                    strides=strides,
                    norm=norm,
                    dtype=self.dtype,
                    name=f"layer{i + 1}_{j}",
                )(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)
        return x


resnet18 = functools.partial(ResNet, stage_sizes=(2, 2, 2, 2), block=BasicBlock)
resnet34 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BasicBlock)
resnet50 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block=Bottleneck)
resnet101 = functools.partial(ResNet, stage_sizes=(3, 4, 23, 3), block=Bottleneck)
