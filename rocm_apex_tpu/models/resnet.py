"""ResNet family, TPU-native (NHWC), with SyncBatchNorm option.

The reference's north-star example trains torchvision ResNet-50 under
amp + DDP (reference: examples/imagenet/main_amp.py; the L1 harness
runs b=128 RN50, tests/L1/common/run_test.sh:20-27). This is that model
as flax modules: NHWC layout (TPU conv-native; the reference reaches
the same layout via --channels-last), `nn.BatchNorm` by default or the
framework's cross-replica `SyncBatchNorm` when `sync_bn_axis` is set
(reference: apex.parallel.SyncBatchNorm + convert_syncbn_model).
"""

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocm_apex_tpu.parallel import SyncBatchNorm

__all__ = [
    "ResNet",
    "BasicBlock",
    "Bottleneck",
    "FoldedConvBN",
    "resnet_tiny",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
]


def _norm(cfg_axis, dtype):
    if cfg_axis is not None:
        return functools.partial(
            SyncBatchNorm,
            momentum=0.1,
            axis_name=cfg_axis,
            channel_last=True,
            dtype=dtype,
        )
    return functools.partial(
        nn.BatchNorm, momentum=0.9, epsilon=1e-5, dtype=dtype
    )


def _is_plain_bn(norm) -> bool:
    """True when `norm` is the plain nn.BatchNorm partial (the fold's
    moment identities would need cross-replica psums under SyncBN)."""
    return getattr(norm, "func", None) is nn.BatchNorm


def _fold_bn_kwargs(norm) -> dict:
    """momentum/epsilon the fold must reproduce: the partial's values
    when given, else flax `nn.BatchNorm`'s OWN defaults (0.99 / 1e-5) —
    a user partial that omits them must behave identically folded or
    unfolded, so the fallback cannot be this module's 0.9 preference."""
    kw = getattr(norm, "keywords", {})
    return {
        "momentum": kw.get("momentum", nn.BatchNorm.momentum),
        "epsilon": kw.get("epsilon", nn.BatchNorm.epsilon),
    }


class FoldedConvBN(nn.Module):
    """1×1 conv + BatchNorm on a no-ReLU edge in ONE pass over the
    input — the projection-shortcut (downsample) fold.

    Training-mode BN statistics of a 1×1 conv's output are EXACT
    functions of the input's first and second moments:

        z = xs · W          (xs = the strided input view, (T, Cin))
        mean_z = mean_x · W
        var_z  = diag(Wᵀ G W) / T − mean_z²,   G = xsᵀ xs

    so folding γ·rsqrt(var+ε) into W (and the matching shift into a
    bias) yields the NORMALIZED output from a single matmul over xs —
    the conv output is never written out for the stats read or the
    normalize read. G costs one small (Cin, Cin) MXU matmul over data
    the conv reads anyway. Measured 3.9× on the isolated stage-2
    downsample chain (0.689 → 0.175 ms, BASELINE.md round-5 RN50
    section); this is the graph-level version of the write-once
    bottleneck structure the round-4 Pallas tap kernels could not win
    at the conv itself. Eval mode is the classic inference BN fold of
    the running statistics. Running stats update exactly as
    `nn.BatchNorm(momentum, epsilon)` (fp32, fast-variance
    convention)."""

    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32
    # defaults mirror flax nn.BatchNorm's own (the module this fold
    # must be a drop-in for); the ResNet blocks pass their norm
    # partial's values through _fold_bn_kwargs
    momentum: float = nn.BatchNorm.momentum
    epsilon: float = nn.BatchNorm.epsilon

    @nn.compact
    def __call__(self, x, train: bool = True):
        cin = x.shape[-1]
        kernel = self.param(
            "conv_kernel",
            nn.initializers.lecun_normal(),
            (1, 1, cin, self.features),
            jnp.float32,
        )
        scale = self.param(
            "bn_scale", nn.initializers.ones_init(), (self.features,),
            jnp.float32,
        )
        bias = self.param(
            "bn_bias", nn.initializers.zeros_init(), (self.features,),
            jnp.float32,
        )
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda s: jnp.zeros(s, jnp.float32), (self.features,),
        )
        ra_var = self.variable(
            "batch_stats", "var",
            lambda s: jnp.ones(s, jnp.float32), (self.features,),
        )

        s = self.strides
        xs = x[:, ::s, ::s, :] if s > 1 else x
        w = kernel.reshape(cin, self.features).astype(jnp.float32)

        if not train:
            mean = ra_mean.value
            var = ra_var.value
        else:
            n, h, ww, _ = xs.shape
            t = n * h * ww
            x2 = xs.reshape(t, cin)
            mean_x = jnp.mean(x2.astype(jnp.float32), axis=0)
            gram = jnp.einsum(
                "tc,td->cd", x2, x2, preferred_element_type=jnp.float32
            )
            mean = mean_x @ w
            # fast-variance convention (flax _compute_stats):
            # E[z²] − E[z]², clipped at zero against roundoff
            var = jnp.maximum(
                jnp.einsum("cd,ce,ed->d", w, gram, w) / t - mean * mean,
                0.0,
            )
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value
                    + (1.0 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value
                    + (1.0 - self.momentum) * var
                )

        rs = jax.lax.rsqrt(var + self.epsilon)
        w_fold = (w * (scale * rs)[None, :]).astype(self.dtype)
        b_fold = bias - scale * rs * mean
        y = jnp.einsum(
            "nhwc,cd->nhwd",
            xs.astype(self.dtype),
            w_fold,
            preferred_element_type=jnp.float32,
        ) + b_fold
        return y.astype(self.dtype)


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    norm: Any = None
    dtype: jnp.dtype = jnp.float32
    fold_downsample: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(
            self.filters, (3, 3), (self.strides, self.strides),
            padding=1, use_bias=False, dtype=self.dtype, name="conv1",
        )(x)
        y = self.norm(name="bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.filters, (3, 3), padding=1, use_bias=False,
            dtype=self.dtype, name="conv2",
        )(y)
        y = self.norm(name="bn2")(y, use_running_average=not train)
        if residual.shape != y.shape:
            if self.fold_downsample and _is_plain_bn(self.norm):
                # no-ReLU edge: conv + BN in one pass over the input.
                # OPT-IN: wins forward-only inference (3.9x isolated);
                # the TRAIN step loses ~3 ms net to the fold backward
                # (xs read twice more + strided-slice materialization)
                # — BASELINE.md round-5 RN50 section has the numbers
                residual = FoldedConvBN(
                    self.filters, self.strides, dtype=self.dtype,
                    name="downsample_fold",
                    **_fold_bn_kwargs(self.norm),
                )(residual, train)
            else:
                residual = nn.Conv(
                    self.filters, (1, 1), (self.strides, self.strides),
                    use_bias=False, dtype=self.dtype,
                    name="downsample_conv",
                )(residual)
                residual = self.norm(name="downsample_bn")(
                    residual, use_running_average=not train
                )
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1
    norm: Any = None
    dtype: jnp.dtype = jnp.float32
    expansion: int = 4
    fold_downsample: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(
            self.filters, (1, 1), use_bias=False, dtype=self.dtype,
            name="conv1",
        )(x)
        y = self.norm(name="bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.filters, (3, 3), (self.strides, self.strides), padding=1,
            use_bias=False, dtype=self.dtype, name="conv2",
        )(y)
        y = self.norm(name="bn2")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.filters * self.expansion, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv3",
        )(y)
        y = self.norm(name="bn3")(y, use_running_average=not train)
        if residual.shape != y.shape:
            if self.fold_downsample and _is_plain_bn(self.norm):
                # no-ReLU edge: conv + BN in one pass over the input
                # (opt-in; see BasicBlock note and BASELINE.md)
                residual = FoldedConvBN(
                    self.filters * self.expansion, self.strides,
                    dtype=self.dtype, name="downsample_fold",
                    **_fold_bn_kwargs(self.norm),
                )(residual, train)
            else:
                residual = nn.Conv(
                    self.filters * self.expansion, (1, 1),
                    (self.strides, self.strides), use_bias=False,
                    dtype=self.dtype, name="downsample_conv",
                )(residual)
                residual = self.norm(name="downsample_bn")(
                    residual, use_running_average=not train
                )
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """NHWC ResNet. `sync_bn_axis` switches BN to cross-replica stats.

    `fused=True` routes every stride-1 bottleneck block through the
    fused Pallas kernel chain (ops/fused_bottleneck.py: BN-apply
    prologues, conv-on-MXU, BN-stats epilogues, merged backward) — the
    reference's cudnn fused-bottleneck analogue (reference:
    apex/contrib/bottleneck/bottleneck.py:112). Stride-2 blocks and the
    stem keep the XLA path; SyncBatchNorm and BasicBlock nets ignore
    the flag.
    """

    stage_sizes: Sequence[int]
    block: Any = Bottleneck
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.float32
    sync_bn_axis: Optional[str] = None
    fused: bool = False
    # opt-in projection-shortcut fold (FoldedConvBN): a win for
    # forward-only inference, a net loss for the train step —
    # BASELINE.md round-5 RN50 section has the measurements
    fold_downsample: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = _norm(self.sync_bn_axis, self.dtype)
        x = nn.Conv(
            self.num_filters, (7, 7), (2, 2), padding=3, use_bias=False,
            dtype=self.dtype, name="conv1",
        )(x)
        x = norm(name="bn1")(x, use_running_average=not train)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=((1, 1), (1, 1)))
        use_fused = (
            self.fused
            and self.block is Bottleneck
            and self.sync_bn_axis is None
        )
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                filters = self.num_filters * 2**i
                if use_fused and strides == 1:
                    from rocm_apex_tpu.contrib.bottleneck import (
                        FusedBottleneck,
                    )

                    x = FusedBottleneck(
                        in_channels=x.shape[-1],
                        bottleneck_channels=filters,
                        out_channels=filters * 4,
                        dtype=self.dtype,
                        name=f"layer{i + 1}_{j}",
                    )(x, train)
                    continue
                x = self.block(
                    filters,
                    strides=strides,
                    norm=norm,
                    dtype=self.dtype,
                    fold_downsample=self.fold_downsample,
                    name=f"layer{i + 1}_{j}",
                )(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)
        return x


# test/smoke vehicle: the smallest ResNet that still exercises BN,
# blocks, and the projection shortcut through the SAME code paths —
# the L1 determinism cross-product and example smokes use it so their
# per-config compiles cost seconds, not minutes (the literal RN50
# north-star config keeps its own full-scale L1 test)
resnet_tiny = functools.partial(
    ResNet, stage_sizes=(1, 1), block=BasicBlock, num_filters=8
)
resnet18 = functools.partial(ResNet, stage_sizes=(2, 2, 2, 2), block=BasicBlock)
resnet34 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BasicBlock)
resnet50 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block=Bottleneck)
resnet101 = functools.partial(ResNet, stage_sizes=(3, 4, 23, 3), block=Bottleneck)
