"""Megatron-style BERT, TPU-native.

Rebuild of the reference's standalone BERT test model
(reference: apex/transformer/testing/standalone_bert.py:1-217 —
bert_extended_attention_mask, bert_position_ids, BertLanguageModelHead,
post_language_model_processing, BertModel) over the same shard_map
tensor-parallel blocks as models/gpt.py. Bidirectional (padding-mask)
attention, learned positions + token-type embeddings, tied masked-LM
head, optional binary (NSP) head.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocm_apex_tpu.normalization import MixedFusedLayerNorm
from rocm_apex_tpu.models.gpt import (
    GPTConfig,
    ParallelTransformer,
    TransformerEmbedding,
    _init,
    _serial_cross_entropy,
)
from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.transformer.tensor_parallel import ColumnParallelLinear
from rocm_apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)

__all__ = ["BertConfig", "BertModel", "bert_extended_attention_mask"]


@dataclasses.dataclass(frozen=True)
class BertConfig(GPTConfig):
    """GPT hyperparameters + BERT extras."""

    num_token_types: int = 2
    add_binary_head: bool = True


def bert_extended_attention_mask(attention_mask: jnp.ndarray) -> jnp.ndarray:
    """[b, s] padding mask (1 = keep) -> [b, 1, s, s] True = masked
    (reference: standalone_bert.py bert_extended_attention_mask)."""
    m = attention_mask.astype(bool)
    # attend only where both query and key positions are valid
    ext = m[:, None, :, None] & m[:, None, None, :]
    return ~ext


class BertLMHead(nn.Module):
    """Masked-LM head: dense + gelu + LN, then tied vocab projection
    (reference: standalone_bert.py BertLanguageModelHead)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, hidden, embedding: TransformerEmbedding):
        cfg = self.cfg
        h = nn.Dense(
            cfg.hidden_size,
            dtype=cfg.dtype,
            param_dtype=cfg.params_dtype,
            kernel_init=_init(cfg),
            name="dense",
        )(hidden)
        h = nn.gelu(h)
        h = MixedFusedLayerNorm(
            cfg.hidden_size, eps=cfg.layernorm_epsilon, name="layernorm"
        )(h)
        return embedding.attend(h)


class BertModel(nn.Module):
    """Embeddings -> bidirectional ParallelTransformer -> (pooler,
    LM head, binary head). With ``lm_labels`` returns
    ``(per_token_lm_loss, binary_logits)``; otherwise
    ``(lm_logits, binary_logits)``. ``binary_logits`` is None without
    the binary head (reference: standalone_bert.py BertModel.forward)."""

    cfg: BertConfig

    def setup(self):
        cfg = self.cfg
        self.embedding = TransformerEmbedding(cfg, name="embedding")
        self.tokentype_embeddings = self.param(
            "tokentype_embeddings",
            _init(cfg),
            (cfg.num_token_types, cfg.hidden_size),
            cfg.params_dtype,
        )
        self.transformer = ParallelTransformer(
            cfg, attn_mask_type="padding", name="transformer"
        )
        self.lm_head = BertLMHead(cfg, name="lm_head")
        if cfg.add_binary_head:
            self.pooler = nn.Dense(
                cfg.hidden_size,
                dtype=cfg.dtype,
                param_dtype=cfg.params_dtype,
                kernel_init=_init(cfg),
                name="pooler",
            )
            self.binary_head = nn.Dense(
                2,
                dtype=jnp.float32,
                param_dtype=cfg.params_dtype,
                kernel_init=_init(cfg),
                name="binary_head",
            )

    def __call__(
        self,
        tokens,
        attention_mask=None,
        tokentype_ids=None,
        lm_labels=None,
        deterministic: bool = True,
    ):
        cfg = self.cfg
        # attention_mask=None means NO padded positions: keep it None
        # so the attention layer takes the dense packed flash path
        # (merged single-tile backward, no (b, s, s) zero-bias tensor)
        # instead of masking against an all-keep tensor
        ext_mask = (
            bert_extended_attention_mask(attention_mask)
            if attention_mask is not None
            else None
        )

        x = self.embedding(tokens, None, deterministic)
        if tokentype_ids is not None:
            x = x + jnp.take(
                self.tokentype_embeddings, tokentype_ids, axis=0
            ).astype(cfg.dtype)
        x = self.transformer(
            x, attention_mask=ext_mask, deterministic=deterministic
        )

        binary_logits = None
        if cfg.add_binary_head:
            pooled = jnp.tanh(self.pooler(x[:, 0]))
            binary_logits = self.binary_head(pooled)

        lm_logits = self.lm_head(x, self.embedding)
        if lm_labels is None:
            return lm_logits, binary_logits
        tp = cfg.tensor_parallel_size or 1
        # compute-dtype logits: both CE paths upcast internally per
        # tile (no fp32 logits copy in HBM — see models/gpt.py)
        if tp > 1 or parallel_state.model_parallel_is_initialized():
            losses = vocab_parallel_cross_entropy(
                lm_logits, lm_labels, cfg.tensor_axis
            )
        else:
            losses = _serial_cross_entropy(lm_logits, lm_labels)
        return losses, binary_logits
