"""DCGAN generator/discriminator, TPU-native (NHWC).

The reference ships DCGAN as an amp example and the SyncBatchNorm
showcase (reference: examples/dcgan/main_amp.py; BASELINE.md config 3
"DCGAN with SyncBatchNorm allreduce over ICI"). Standard DCGAN
topology: transposed-conv generator, strided-conv discriminator,
BatchNorm (optionally cross-replica) everywhere but the G output / D
input layers.
"""

import functools
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from rocm_apex_tpu.parallel import SyncBatchNorm

__all__ = ["Generator", "Discriminator"]


def _norm(axis, dtype):
    if axis is not None:
        return functools.partial(
            SyncBatchNorm, axis_name=axis, channel_last=True, dtype=dtype
        )
    return functools.partial(nn.BatchNorm, momentum=0.9, dtype=dtype)


class Generator(nn.Module):
    """z (b, 1, 1, nz) -> image (b, 64, 64, nc)."""

    nz: int = 100
    ngf: int = 64
    nc: int = 3
    dtype: jnp.dtype = jnp.float32
    sync_bn_axis: Optional[str] = None

    @nn.compact
    def __call__(self, z, train: bool = True):
        norm = _norm(self.sync_bn_axis, self.dtype)
        chans = [self.ngf * 8, self.ngf * 4, self.ngf * 2, self.ngf]
        x = z
        for i, ch in enumerate(chans):
            if i == 0:
                x = nn.ConvTranspose(
                    ch, (4, 4), (1, 1), padding="VALID",
                    use_bias=False, dtype=self.dtype, name=f"deconv{i}",
                )(x)
            else:
                x = nn.ConvTranspose(
                    ch, (4, 4), (2, 2), padding="SAME",
                    use_bias=False, dtype=self.dtype, name=f"deconv{i}",
                )(x)
            x = norm(name=f"bn{i}")(x, use_running_average=not train)
            x = nn.relu(x)
        x = nn.ConvTranspose(
            self.nc, (4, 4), (2, 2), padding="SAME",
            use_bias=False, dtype=self.dtype, name="deconv_out",
        )(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    """image (b, 64, 64, nc) -> logit (b, 1)."""

    ndf: int = 64
    nc: int = 3
    dtype: jnp.dtype = jnp.float32
    sync_bn_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = _norm(self.sync_bn_axis, self.dtype)
        chans = [self.ndf, self.ndf * 2, self.ndf * 4, self.ndf * 8]
        for i, ch in enumerate(chans):
            x = nn.Conv(
                ch, (4, 4), (2, 2), padding=((1, 1), (1, 1)),
                use_bias=False, dtype=self.dtype, name=f"conv{i}",
            )(x)
            if i > 0:
                x = norm(name=f"bn{i}")(x, use_running_average=not train)
            x = nn.leaky_relu(x, 0.2)
        x = nn.Conv(
            1, (4, 4), (1, 1), padding="VALID", use_bias=False,
            dtype=self.dtype, name="conv_out",
        )(x)
        return x.reshape(x.shape[0], 1)
