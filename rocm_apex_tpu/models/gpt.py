"""Megatron-style GPT, TPU-native.

Rebuild of the reference's standalone GPT
(reference: apex/transformer/testing/standalone_gpt.py — ParallelMLP:234,
ParallelAttention:283, ParallelTransformerLayer:575,
ParallelTransformer:711, Embedding:998, TransformerLanguageModel:1147)
as flax modules over the shard_map tensor-parallel layers. Departures by
design:

* activations are ``[batch, seq, hidden]`` (TPU-friendly; Megatron uses
  ``[seq, batch, hidden]`` for NCCL-contiguity reasons that do not apply);
* core attention uses the Pallas scaled causal/masked softmax with no
  2048-seqlen ceiling (reference fused_softmax.py:160) and bf16 compute;
* layers are uniform blocks so a stack maps 1:1 onto the pipeline
  schedules' stacked-params convention (schedules.py), and onto
  `lax.scan` for compile-time-friendly deep stacks;
* dropout uses flax functional RNG — per-TP-rank independence comes from
  folding the tp rank into the key, the analogue of the reference's
  CudaRNGStatesTracker (tensor_parallel/random.py:113-193).

The TP degree is taken from ``config.tensor_parallel_size``; with 1 the
modules run unsharded (GSPMD/pjit users annotate instead).
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from rocm_apex_tpu.normalization import MixedFusedLayerNorm
from rocm_apex_tpu.ops.flash_attention import flash_attention
from rocm_apex_tpu.ops.lora import apply_lora
from rocm_apex_tpu.ops.xentropy import softmax_cross_entropy_loss_fused
from rocm_apex_tpu.ops.softmax import (
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)
from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    gather_from_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
)
from rocm_apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)

__all__ = [
    "GPTConfig",
    "GPTModel",
    "ParallelMLP",
    "ParallelAttention",
    "ParallelTransformerLayer",
    "ParallelTransformer",
    "TransformerEmbedding",
    "gpt_loss_fn",
    "gpt_pipeline_functions",
]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Model hyperparameters; the static subset of the reference's
    Megatron argument system (apex/transformer/testing/arguments.py)."""

    vocab_size: int = 32000
    hidden_size: int = 1024
    num_layers: int = 12
    num_attention_heads: int = 16
    max_position_embeddings: int = 2048
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layernorm_epsilon: float = 1e-5
    apply_residual_connection_post_layernorm: bool = False
    # fp32 params + bf16 compute = the O5/bf16-master recipe.
    params_dtype: Any = jnp.float32
    dtype: Any = jnp.bfloat16
    tensor_parallel_size: Optional[int] = None  # None -> parallel_state
    tensor_axis: str = parallel_state.TENSOR_AXIS
    init_method_std: float = 0.02
    use_pallas_softmax: bool = True
    # "flash" (Pallas flash attention, no seqlen ceiling — the perf
    # path), "fused_softmax" (materialized scores + Pallas softmax,
    # reference csrc/megatron semantics), "jnp" (plain XLA fallback).
    # flash has no in-kernel dropout: with attention_dropout > 0 in
    # training mode the fused_softmax path is used instead.
    attention_impl: str = "flash"
    # per-layer activation checkpointing (reference:
    # tensor_parallel/random.py:224-293 CheckpointFunction; here it is
    # jax.checkpoint/remat — RNG replay is free with functional PRNG)
    checkpoint_activations: bool = False
    # LM-head loss semantics (plumbed into both CE paths): label
    # smoothing epsilon, and the label id whose rows get zero loss and
    # zero gradient (None = every label contributes)
    label_smoothing: float = 0.0
    ignore_index: Optional[int] = None
    # chunked fused linear+CE head (ops/linear_xentropy.py): the
    # (b·s, vocab) logits and dlogits never materialize in HBM — per-
    # chunk tiles are projected, reduced, and contracted back into
    # dx/dW in one pass. False restores the materialized head
    # (attend + softmax_cross_entropy_loss_fused), which trades ~2
    # logits-sized HBM buffers for no chunk-loop/dW-accumulator
    # overhead — see docs/perf.md for when that wins.
    fused_lm_head: bool = True
    # rows per chunk of the fused head (None = the op's default,
    # chunk*vocab ~ 2^27 elements)
    lm_head_chunk_size: Optional[int] = None
    # sequence/context parallelism (capability beyond the reference):
    # when set to a bound mesh axis name, the model runs on LOCAL
    # sequence shards — causal attention becomes ring flash attention
    # over the axis and position embeddings offset by the shard start.
    # Requires attention_impl="flash" and contiguous axis-order sharding.
    context_parallel_axis: Optional[str] = None
    # Megatron-style sequence parallelism over the TENSOR axis
    # (Korthikanti et al.): activations between the column→row TP
    # pairs — layernorms, dropout, residual stream — hold 1/tp of the
    # sequence; the TP-edge collectives become all-gather (entry) and
    # reduce-scatter (exit) on the sequence dim. Unlike
    # context_parallel_axis this reuses the TP ranks (no extra mesh
    # axis) and attention still sees the full sequence; the two cannot
    # compose (both shard the sequence dim).
    sequence_parallel: bool = False
    # fuse the sequence-parallel edge collectives into the adjacent
    # matmuls as ppermute-chunked rings (ops/collective_matmul.py,
    # arXiv 2305.06942): each ICI hop hides under a partial matmul and
    # the gathered (b, s, h) activation never materializes.
    collective_matmul: bool = False
    # ring piece size in rows (None = one piece per shard; a chunk
    # that does not tile the shard falls back to the plain collective)
    collective_matmul_chunk: Optional[int] = None
    # wire dtype for the collective-matmul rings: "int8" quantizes each
    # ring hop's payload with per-row fp32 scale sidecars
    # (ops/quantized_collectives.py); only meaningful with
    # collective_matmul=True — the plain lax collectives stay fp32
    comm_dtype: str = "fp32"
    # activation-RMS telemetry taps (rocm_apex_tpu.monitor): each layer
    # sows the RMS of its attention and MLP outputs (and the model the
    # final hidden state) into the "intermediates" collection as
    # (sum_of_squares, count) pairs — psum'd over the tensor axis where
    # the activation is a sequence shard, so the finalized RMS
    # (monitor.activation_stats) is the GLOBAL statistic. Off by
    # default: the sums are extra reductions on the hot path. Callers
    # opt in per apply with mutable=["intermediates"]; without it the
    # sows are flax no-ops.
    activation_stats: bool = False

    def __post_init__(self):
        if self.sequence_parallel and self.context_parallel_axis is not None:
            raise ValueError(
                "sequence_parallel shards the sequence over the tensor "
                "axis and context_parallel_axis shards it over "
                f"{self.context_parallel_axis!r}: the axes collide on "
                "the sequence dimension — enable one or the other"
            )

    @property
    def ffn_size(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_attention_heads == 0
        return self.hidden_size // self.num_attention_heads


def _init(cfg: GPTConfig):
    return nn.initializers.normal(stddev=cfg.init_method_std)


def _resolve_tp(cfg: GPTConfig) -> int:
    return cfg.tensor_parallel_size or (
        parallel_state.get_tensor_model_parallel_world_size()
        if parallel_state.model_parallel_is_initialized()
        else 1
    )


def _sp_active(cfg: GPTConfig, tp: int) -> bool:
    return cfg.sequence_parallel and tp > 1


def _sp_kwargs(cfg: GPTConfig, tp: int) -> dict:
    """Constructor kwargs routing the sequence-parallel / collective-
    matmul config into a Column/RowParallelLinear."""
    if not _sp_active(cfg, tp):
        return {}
    return dict(
        sequence_parallel=True,
        collective_matmul=cfg.collective_matmul,
        collective_matmul_chunk=cfg.collective_matmul_chunk,
        comm_dtype=cfg.comm_dtype,
    )


class _Dropout(nn.Module):
    """Dropout that folds mesh-axis ranks into the RNG so shards draw
    independent masks: the context axis for sequence shards and the
    tensor axis where the dropped tensor is TP-sharded (attention
    probs, disjoint head shards per rank) — the analogue of the
    reference's get_cuda_rng_tracker().fork()
    (tensor_parallel/random.py:58)."""

    rate: float
    cp_axis: Optional[str] = None
    tp_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        if deterministic or self.rate == 0.0:
            return x
        rng = self.make_rng("dropout")
        for axis in (self.cp_axis, self.tp_axis):
            if axis is not None:
                rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        keep = jax.random.bernoulli(rng, 1.0 - self.rate, x.shape)
        return jnp.where(keep, x / (1.0 - self.rate), 0.0).astype(x.dtype)


def _ln_sync_axis(cfg: GPTConfig) -> Optional[str]:
    """LN affine params are replicated but, under sequence parallelism,
    normalize shard-local rows — their grads psum over the tensor axis
    (MixedFusedLayerNorm.grad_sync_axis)."""
    return (
        cfg.tensor_axis if _sp_active(cfg, _resolve_tp(cfg)) else None
    )


def _hidden_dropout_mod(cfg: GPTConfig) -> "_Dropout":
    """Hidden-dropout module with the shard axes folded in: the
    context axis for CP shards, the tensor axis under sequence
    parallelism (the hidden stream is a sequence shard there too)."""
    return _Dropout(
        cfg.hidden_dropout,
        cfg.context_parallel_axis,
        tp_axis=(
            cfg.tensor_axis if _sp_active(cfg, _resolve_tp(cfg)) else None
        ),
    )


def _scaled_init(cfg: GPTConfig):
    """Output-layer init scaled by 1/sqrt(2*num_layers), Megatron's
    scheme for residual-path projections (standalone_gpt.py uses
    scaled_init_method_normal)."""
    return nn.initializers.normal(
        stddev=cfg.init_method_std / np.sqrt(2.0 * cfg.num_layers)
    )


def _use_ln_dropout(cfg: GPTConfig, deterministic: bool) -> bool:
    """Hidden dropout fuses into the residual-LN kernels on TPU (the
    keep mask regenerated in-kernel from a scalar seed — no u32 mask
    buffers in HBM, measured ~3 ms/step on the 134M training config).
    Pre-LN only: the post-LN variant's eager adds have no kernel to
    ride."""
    from rocm_apex_tpu.ops._pallas import on_tpu

    return (
        cfg.hidden_dropout > 0.0
        and not deterministic
        and not cfg.apply_residual_connection_post_layernorm
        and on_tpu()
    )


def _hidden_dropout_seed(mod: nn.Module, cfg: GPTConfig):
    """Per-site int32 scalar seed for the in-kernel hidden dropout;
    folds the context-parallel rank — and the tensor rank under
    sequence parallelism, where the hidden stream is also a sequence
    shard — so shards draw independent masks (the _Dropout axis
    rule)."""
    rng = mod.make_rng("dropout")
    if cfg.context_parallel_axis is not None:
        rng = jax.random.fold_in(
            rng, jax.lax.axis_index(cfg.context_parallel_axis)
        )
    if _sp_active(cfg, _resolve_tp(cfg)):
        rng = jax.random.fold_in(rng, jax.lax.axis_index(cfg.tensor_axis))
    return jax.random.randint(rng, (), 0, 2**31 - 1, jnp.int32)


def _sow_rms(mod: nn.Module, cfg: GPTConfig, name: str, x) -> None:
    """Activation-RMS tap: sow (sum_of_squares, count) under
    ``intermediates/<path>/<name>`` for `monitor.activation_stats` to
    finalize into ``sqrt(sumsq/count)``.

    Under sequence parallelism the tensor is a 1/tp sequence shard, so
    the partial sums psum over the tensor axis — the PR-3 shard-partial
    convention — and every rank sows the identical GLOBAL pair. A flax
    no-op unless the caller passes mutable=["intermediates"]."""
    if not cfg.activation_stats:
        return
    sumsq = jnp.sum(jnp.square(x.astype(jnp.float32)))
    count = jnp.asarray(x.size, jnp.float32)
    if _sp_active(cfg, _resolve_tp(cfg)):
        sumsq = jax.lax.psum(sumsq, cfg.tensor_axis)
        count = jax.lax.psum(count, cfg.tensor_axis)
    mod.sow("intermediates", name, (sumsq, count))


class ParallelMLP(nn.Module):
    """h → 4h (column-parallel) → gelu → 4h → h (row-parallel)
    (reference: standalone_gpt.py:234-281)."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        sp_kw = _sp_kwargs(cfg, _resolve_tp(cfg))
        h, _ = ColumnParallelLinear(
            cfg.hidden_size,
            cfg.ffn_size,
            gather_output=False,
            init_method=_init(cfg),
            params_dtype=cfg.params_dtype,
            dtype=cfg.dtype,
            world_size=cfg.tensor_parallel_size,
            axis_name=cfg.tensor_axis,
            name="dense_h_to_4h",
            **sp_kw,
        )(x)
        h = nn.gelu(h)
        y, _ = RowParallelLinear(
            cfg.ffn_size,
            cfg.hidden_size,
            input_is_parallel=True,
            init_method=_scaled_init(cfg),
            params_dtype=cfg.params_dtype,
            dtype=cfg.dtype,
            world_size=cfg.tensor_parallel_size,
            axis_name=cfg.tensor_axis,
            name="dense_4h_to_h",
            **sp_kw,
        )(h)
        return y


class ParallelAttention(nn.Module):
    """Self-attention with TP-sharded heads
    (reference: standalone_gpt.py:283-574): column-parallel fused QKV,
    scaled-masked-softmax core, row-parallel output projection.

    ``attn_mask_type``: 'causal' uses the Pallas upper-triang softmax;
    'padding' takes an explicit mask (True = masked).
    """

    cfg: GPTConfig
    attn_mask_type: str = "causal"

    @nn.compact
    def __call__(
        self,
        x,
        attention_mask=None,
        deterministic: bool = True,
        cache=None,
        chunk=None,
        adapters=None,
    ):
        cfg = self.cfg
        tp = cfg.tensor_parallel_size or (
            parallel_state.get_tensor_model_parallel_world_size()
            if parallel_state.model_parallel_is_initialized()
            else 1
        )
        nh_local = cfg.num_attention_heads // tp
        hd = cfg.head_dim
        b, sq, _ = x.shape
        sp = _sp_active(cfg, tp)
        if sp:
            # x is the local sequence shard; the QKV projection's
            # internal all-gather restores the full sequence, which is
            # what every attention path below operates on. The PACKED
            # chunk path composes: the chunk stream is a flat token
            # axis (slot/position indirection rides in `chunk`, not in
            # the sequence dim), so scattering it across ranks and
            # all-gathering inside the projection reconstructs exactly
            # the full chunk. Plain cached decode does not (its seq
            # axis is width-1 per slot and cannot be seq-sharded).
            if cache is not None and chunk is None:
                raise ValueError(
                    "sequence_parallel composes with KV-cached inference "
                    "only on the packed chunk path (the decode step's "
                    "width-1 sequence axis cannot be sequence-sharded)"
                )
            sq = sq * tp

        # KV-cached inference (cache = per-layer (k_buf, v_buf, lengths)
        # from the inference package's KVCache): causal only, and
        # deterministic — decode never sees dropout
        if cache is not None:
            if self.attn_mask_type != "causal":
                raise ValueError(
                    "KV-cached attention is causal-only "
                    f"(got attn_mask_type={self.attn_mask_type!r})"
                )
            if not deterministic:
                raise ValueError(
                    "KV-cached attention requires deterministic=True"
                )

        scale = 1.0 / np.sqrt(hd)
        # in-kernel flash dropout needs the TPU PRNG (no interpret-mode
        # lowering) and is not available on the ring (CP) path
        from rocm_apex_tpu.ops._pallas import on_tpu

        dropout_active = cfg.attention_dropout > 0.0 and not deterministic
        # in-kernel dropout covers BOTH mask types: causal rides the
        # packed kernels, padding rides the additive-bias kernels (the
        # reference's fmha/multihead_attn dropout kernels serve BERT's
        # bidirectional masks the same way)
        use_flash_dropout = (
            cfg.attention_impl == "flash"
            and dropout_active
            and self.attn_mask_type in ("causal", "padding")
            and cfg.context_parallel_axis is None
            and on_tpu()
        )
        use_flash = cfg.attention_impl == "flash" and (
            not dropout_active or use_flash_dropout
        )
        # packed path: causal, or FULL bidirectional ("padding" type
        # with no mask tensor — BERT with no padded positions): the
        # dense packed kernels + merged single-tile backward serve it
        # with causal=False, and no (b, s, s) zero-bias materializes
        will_pack = (
            use_flash
            and (
                self.attn_mask_type == "causal"
                or (
                    self.attn_mask_type == "padding"
                    and attention_mask is None
                )
            )
            and cfg.context_parallel_axis is None
            and hd % 128 == 0
            # cached paths materialize k/v (they must land in the
            # cache buffers), so the zero-relayout packed kernels —
            # which read q/k/v straight out of the fused projection —
            # do not apply; the projection bias stays in the matmul
            and cache is None
        )
        # packed path: the projection bias rides into the attention
        # kernels (added on tile load; bias-grad partials emitted from
        # VMEM in backward) — param structure is unchanged
        qkv, qkv_bias = ColumnParallelLinear(
            cfg.hidden_size,
            3 * cfg.hidden_size,
            gather_output=False,
            skip_bias_add=will_pack,
            init_method=_init(cfg),
            params_dtype=cfg.params_dtype,
            dtype=cfg.dtype,
            world_size=cfg.tensor_parallel_size,
            axis_name=cfg.tensor_axis,
            name="query_key_value",
            **_sp_kwargs(cfg, tp),
        )(x)
        if adapters is not None:
            # multi-LoRA serving: segmented per-token low-rank delta
            # gathered from the packed adapter pool (ops/lora.py).
            # Adapter ids are DATA, so any tenant mix — and any
            # park/reclaim churn in the pool — rides this same trace.
            qkv = apply_lora(
                qkv, x, adapters["qkv"], adapters["ids"],
                adapters["active"],
            )
        qkv = qkv.reshape(b, sq, nh_local, 3 * hd)
        if cfg.context_parallel_axis is not None and (
            not use_flash or self.attn_mask_type != "causal" or dropout_active
        ):
            # silently attending within the local shard only would be a
            # wrong model; context parallelism rides the ring-flash path
            raise ValueError(
                "context_parallel_axis requires attention_impl='flash', "
                "causal masking, and attention_dropout=0 in training "
                f"(got impl={cfg.attention_impl!r}, "
                f"mask={self.attn_mask_type!r}, "
                f"attn_dropout={cfg.attention_dropout})"
            )
        use_pallas_softmax = (
            cfg.use_pallas_softmax and cfg.attention_impl != "jnp"
        )
        # packed path: causal flash with hd % 128 == 0 reads q/k/v tiles
        # straight out of the fused projection output — no split, no
        # transposes, and the context lands output-projection-ready
        # (measured ~8 ms/step of relayout on the 134M bench otherwise)


        def _dropout_seed():
            rng = self.make_rng("dropout")
            if tp > 1:
                # the head shards are disjoint per TP rank; without the
                # fold every rank's kernel seeds the same (b, qi, ki)
                # streams -> correlated masks
                rng = jax.random.fold_in(
                    rng, jax.lax.axis_index(cfg.tensor_axis)
                )
            return jax.random.randint(rng, (), 0, 2**31 - 1, jnp.int32)

        new_kv = None
        if cache is not None and chunk is not None:
            # ---- chunked prefill: one PACKED token chunk, one or more
            # slots, attending each slot's existing cache prefix plus
            # intra-chunk causality. `chunk` = (slot_ids, positions),
            # both (budget,) int32; x is the (1, budget, h) packed
            # stream; padding tokens carry slot id == num_slots.
            # `cache` is the 3-tuple contiguous layer view, or the
            # 4-tuple paged view whose last element carries the page
            # table / page size / per-(page, head) int8 scales — the
            # writes scatter through the table and the reads gather
            # through it (ops/paging.py + the paged flash kernels).
            #
            # SPECULATIVE mode: a 3-tuple chunk (slot_ids, positions,
            # commit_slots) splits "who attends" from "who commits".
            # Attention masking still follows `chunk_slots`, but the
            # K/V scatter routes through `commit_slots` — speculative
            # rows carry the num_slots sentinel there, so their K/V
            # never lands in the cache in-trace (the host commits the
            # accepted prefix afterwards via KVCache.write_at, which is
            # what keeps rejected drafts away from shared pages and
            # int8 scales). Each layer then also returns its packed
            # chunk-local (kq, vq) so the host-side commit has the
            # bytes to write.
            if x.shape[0] != 1:
                raise ValueError(
                    "chunked prefill takes one packed stream "
                    f"(batch 1), got batch {x.shape[0]}"
                )
            k_buf, v_buf, lengths = cache[:3]
            paged = cache[3] if len(cache) > 3 else None
            spec = len(chunk) == 3
            chunk_slots, chunk_pos = chunk[0], chunk[1]
            commit_slots = chunk[2] if spec else chunk_slots
            # full packed width: under sequence parallelism x carries
            # only the local shard, but qkv was all-gathered back to
            # the full chunk — sq already accounts for that
            budget = sq
            q, k, v = jnp.split(qkv, 3, axis=-1)  # (1, budget, nh, hd)
            qq, kq, vq = q[0], k[0], v[0]  # (budget, nh, hd)
            k_sc = v_sc = None
            if paged is None:
                num_slots, capacity = k_buf.shape[0], k_buf.shape[1]
                # scatter this chunk's K/V at per-token (slot, position)
                # destinations (in place under jit with donated
                # buffers); out-of-range pad slots are dropped
                k_buf = k_buf.at[commit_slots, chunk_pos].set(
                    kq.astype(k_buf.dtype), mode="drop"
                )
                v_buf = v_buf.at[commit_slots, chunk_pos].set(
                    vq.astype(v_buf.dtype), mode="drop"
                )
                new_kv = (k_buf, v_buf)
            else:
                from rocm_apex_tpu.ops.paging import (
                    paged_scatter,
                    quantized_paged_scatter,
                )

                table = paged["page_table"]
                num_slots = table.shape[0]
                capacity = table.shape[1] * paged["page_size"]
                if paged["k_scale"] is not None:
                    k_buf, k_sc = quantized_paged_scatter(
                        k_buf, paged["k_scale"], table,
                        commit_slots, chunk_pos, kq,
                    )
                    v_buf, v_sc = quantized_paged_scatter(
                        v_buf, paged["v_scale"], table,
                        commit_slots, chunk_pos, vq,
                    )
                    new_kv = (k_buf, v_buf, k_sc, v_sc)
                else:
                    k_buf = paged_scatter(
                        k_buf, table, commit_slots, chunk_pos, kq
                    )
                    v_buf = paged_scatter(
                        v_buf, table, commit_slots, chunk_pos, vq
                    )
                    new_kv = (k_buf, v_buf)
            slot_c = jnp.clip(chunk_slots, 0, num_slots - 1)
            if cfg.attention_impl == "jnp":
                # one-pass reference: the chunk K/V are already in the
                # cache (scatter above), so each token attends its
                # slot's rows [0, pos + 1) — prefix, intra-chunk
                # predecessors, and itself in one bounded softmax. The
                # slot selection rides a one-hot contraction instead of
                # a per-token gather: k_buf[slots] would materialize
                # (budget, capacity, heads, hd) — each slot's cache
                # duplicated once per chunk token (measured as most of
                # the mixed-tick cost on the CPU serve bench). A paged
                # cache reads the table-gathered contiguous view
                # (dequantized when int8) — byte-identical rows when
                # unquantized, so paged-vs-contiguous parity is exact
                # on this path.
                if paged is None:
                    kc_read, vc_read = k_buf, v_buf
                else:
                    from rocm_apex_tpu.ops.paging import paged_view

                    kc_read = paged_view(k_buf, table, scale=k_sc)
                    vc_read = paged_view(v_buf, table, scale=v_sc)
                onehot = (
                    slot_c[:, None] == jnp.arange(num_slots)[None, :]
                ).astype(jnp.float32)  # (budget, num_slots)
                scores = jnp.einsum(
                    "tnd,scnd,ts->tnc",
                    qq.astype(jnp.float32),
                    kc_read.astype(jnp.float32),
                    onehot,
                ) * scale
                col = jnp.arange(capacity)[None, None, :]
                if not spec:
                    bound = (chunk_pos + 1)[:, None, None]
                    scores = jnp.where(col < bound, scores, -jnp.inf)
                    probs = jax.nn.softmax(scores, axis=-1)
                    ctx_t = jnp.einsum(
                        "tnc,scnd,ts->tnd",
                        probs,
                        vc_read.astype(jnp.float32),
                        onehot,
                    )
                else:
                    # speculative rows are NOT in the cache (their
                    # scatter is deferred to the host commit), so the
                    # one-pass read above can only cover each slot's
                    # COMMITTED prefix [0, lengths). Intra-chunk
                    # predecessors + self come straight from the packed
                    # projections — the same two-piece structure the
                    # flash chunk path always had — under ONE softmax
                    # over the concatenated (prefix ++ chunk) axis.
                    bound = lengths[slot_c][:, None, None]
                    scores = jnp.where(col < bound, scores, -jnp.inf)
                    if k_sc is None:
                        # round-trip through the cache dtype so the
                        # intra-chunk read is byte-identical to reading
                        # scattered rows back (greedy parity with the
                        # non-speculative path); int8 pages dequantize
                        # with data-dependent scales, so there the raw
                        # projection is the faithful value
                        kb = kq.astype(k_buf.dtype).astype(jnp.float32)
                        vb = vq.astype(v_buf.dtype).astype(jnp.float32)
                    else:
                        kb = kq.astype(jnp.float32)
                        vb = vq.astype(jnp.float32)
                    scores_b = jnp.einsum(
                        "tnd,jnd->tnj", qq.astype(jnp.float32), kb
                    ) * scale
                    intra = (
                        chunk_slots[None, :] == chunk_slots[:, None]
                    ) & (chunk_pos[None, :] <= chunk_pos[:, None])
                    scores_b = jnp.where(
                        intra[:, None, :], scores_b, -jnp.inf
                    )
                    probs = jax.nn.softmax(
                        jnp.concatenate([scores, scores_b], axis=-1),
                        axis=-1,
                    )
                    ctx_t = jnp.einsum(
                        "tnc,scnd,ts->tnd",
                        probs[..., :capacity],
                        vc_read.astype(jnp.float32),
                        onehot,
                    ) + jnp.einsum(
                        "tnj,jnd->tnd", probs[..., capacity:], vb
                    )
            elif paged is not None:
                # flash paged: the composed op runs the intra-chunk
                # segments kernel + the page-table-gather prefix read
                # (bounded by pages actually live) and merges by lse
                from rocm_apex_tpu.ops.flash_attention_segments import (
                    flash_attention_chunk_paged,
                )

                ctx_t = flash_attention_chunk_paged(
                    qq.transpose(1, 0, 2),
                    kq.transpose(1, 0, 2),
                    vq.transpose(1, 0, 2),
                    chunk_slots,
                    k_buf, v_buf, table, lengths,
                    scale, k_scale=k_sc, v_scale=v_sc,
                )
            else:
                # flash: two pieces merged by log-sum-exp weights.
                # (A) intra-chunk causal attention over the packed
                # stream, segment-masked by slot id (the packed varlen
                # kernel — pads only match each other);
                from rocm_apex_tpu.ops.flash_attention import (
                    flash_attention_decode,
                )
                from rocm_apex_tpu.ops.flash_attention_segments import (
                    flash_attention_segments_with_lse,
                )

                qT = qq.transpose(1, 0, 2)  # (nh, budget, hd)
                o_a, lse_a = flash_attention_segments_with_lse(
                    qT,
                    kq.transpose(1, 0, 2),
                    vq.transpose(1, 0, 2),
                    chunk_slots,
                    causal=True,
                    scale=scale,
                )
                # (B) the whole chunk against every slot's PRE-CHUNK
                # cache prefix — the cache is read once at slot
                # granularity (chunk width, not per-token width), with
                # each slot's bound = its materialized length; rows
                # with an empty prefix merge in at weight zero
                kc = (
                    k_buf.transpose(0, 2, 1, 3)
                    .reshape(num_slots * nh_local, capacity, hd)
                )
                vc = (
                    v_buf.transpose(0, 2, 1, 3)
                    .reshape(num_slots * nh_local, capacity, hd)
                )
                qB = jnp.broadcast_to(
                    qT[None], (num_slots, nh_local, budget, hd)
                ).reshape(num_slots * nh_local, budget, hd)
                o_b, lse_b = flash_attention_decode(
                    qB, kc, vc,
                    jnp.repeat(lengths, nh_local),
                    scale, return_lse=True,
                )
                o_b = o_b.reshape(num_slots, nh_local, budget, hd)
                lse_b = lse_b.reshape(num_slots, nh_local, budget)
                tok = jnp.arange(budget)
                o_b = o_b[slot_c, :, tok]  # (budget, nh, hd)
                lse_b = lse_b[slot_c, :, tok]  # (budget, nh)
                o_a = o_a.transpose(1, 0, 2)  # (budget, nh, hd)
                lse_a = lse_a.transpose(1, 0)  # (budget, nh)
                m = jnp.maximum(lse_a, lse_b)
                w_a = jnp.exp(lse_a - m)
                w_b = jnp.exp(lse_b - m)
                ctx_t = (
                    w_a[..., None] * o_a.astype(jnp.float32)
                    + w_b[..., None] * o_b.astype(jnp.float32)
                ) / (w_a + w_b)[..., None]
            if spec:
                # hand the packed chunk K/V to the host: the engine's
                # post-verify commit writes the ACCEPTED rows (and only
                # those) through KVCache.write_at
                new_kv = new_kv + (kq, vq)
            ctx = ctx_t.astype(cfg.dtype).reshape(
                1, budget, nh_local * hd
            )
        elif cache is not None:
            k_buf, v_buf, lengths = cache[:3]
            paged = cache[3] if len(cache) > 3 else None
            q, k, v = jnp.split(qkv, 3, axis=-1)  # (b, sq, nh, hd)
            k_sc = v_sc = None
            if paged is None:
                # write the new keys/values at each slot's current
                # length (per-row dynamic_update_slice: in place under
                # jit with donated cache buffers). lengths do NOT
                # advance here — every layer writes at the same
                # offsets; the transformer advances once per forward.
                def _write(buf, new, start):
                    return jax.lax.dynamic_update_slice(
                        buf, new.astype(buf.dtype), (start, 0, 0)
                    )

                k_buf = jax.vmap(_write)(k_buf, k, lengths)
                v_buf = jax.vmap(_write)(v_buf, v, lengths)
                new_kv = (k_buf, v_buf)
            else:
                if sq != 1:
                    raise ValueError(
                        "a paged cache serves single-token decode and "
                        "chunked prefill; whole-prompt prefill needs "
                        "the contiguous cache (or chunk=)"
                    )
                from rocm_apex_tpu.ops.paging import (
                    paged_scatter,
                    quantized_paged_scatter,
                )

                table = paged["page_table"]
                # one token per slot at its current length; positions
                # at/past capacity DROP (never clamp into a live —
                # possibly shared — page; the engine masks dead rows
                # by sentineling their lengths to capacity)
                w_slots = jnp.arange(b, dtype=jnp.int32)
                flat_k = k.reshape(b, nh_local, hd)
                flat_v = v.reshape(b, nh_local, hd)
                if paged["k_scale"] is not None:
                    k_buf, k_sc = quantized_paged_scatter(
                        k_buf, paged["k_scale"], table,
                        w_slots, lengths, flat_k,
                    )
                    v_buf, v_sc = quantized_paged_scatter(
                        v_buf, paged["v_scale"], table,
                        w_slots, lengths, flat_v,
                    )
                    new_kv = (k_buf, v_buf, k_sc, v_sc)
                else:
                    k_buf = paged_scatter(
                        k_buf, table, w_slots, lengths, flat_k
                    )
                    v_buf = paged_scatter(
                        v_buf, table, w_slots, lengths, flat_v
                    )
                    new_kv = (k_buf, v_buf)
            qf = q.transpose(0, 2, 1, 3).reshape(b * nh_local, sq, hd)
            if sq == 1:
                # single-token decode against the cache: each slot
                # attends its live prefix [0, lengths + 1) — junk
                # beyond it (evicted predecessors, prefill padding) is
                # masked by the per-row bound
                if paged is not None:
                    capacity = table.shape[1] * paged["page_size"]
                    kv_len_slot = jnp.minimum(lengths + 1, capacity)
                    if cfg.attention_impl != "jnp":
                        from rocm_apex_tpu.ops.flash_attention import (
                            flash_attention_decode_paged,
                        )

                        # the page-table-gather read: HBM traffic is
                        # bounded by pages actually live, not the
                        # fixed-capacity tail
                        ctxf = flash_attention_decode_paged(
                            qf, k_buf, v_buf, table, kv_len_slot,
                            scale, k_scale=k_sc, v_scale=v_sc,
                        )
                    else:
                        from rocm_apex_tpu.ops.paging import paged_view

                        kf = (
                            paged_view(k_buf, table, scale=k_sc)
                            .transpose(0, 2, 1, 3)
                            .reshape(b * nh_local, capacity, hd)
                        )
                        vf = (
                            paged_view(v_buf, table, scale=v_sc)
                            .transpose(0, 2, 1, 3)
                            .reshape(b * nh_local, capacity, hd)
                        )
                        kv_len = jnp.repeat(kv_len_slot, nh_local)
                        scores = jnp.einsum(
                            "bqd,bkd->bqk",
                            qf.astype(jnp.float32),
                            kf.astype(jnp.float32),
                        ) * scale
                        col = jnp.arange(capacity)[None, None, :]
                        scores = jnp.where(
                            col < kv_len[:, None, None], scores,
                            -jnp.inf,
                        )
                        probs = jax.nn.softmax(scores, axis=-1)
                        ctxf = jnp.einsum(
                            "bqk,bkd->bqd", probs,
                            vf.astype(jnp.float32),
                        ).astype(cfg.dtype)
                else:
                    capacity = k_buf.shape[1]
                    kf = (
                        k_buf.transpose(0, 2, 1, 3)
                        .reshape(b * nh_local, capacity, hd)
                    )
                    vf = (
                        v_buf.transpose(0, 2, 1, 3)
                        .reshape(b * nh_local, capacity, hd)
                    )
                    kv_len = jnp.repeat(
                        jnp.minimum(lengths + 1, capacity), nh_local
                    )
                    if cfg.attention_impl == "jnp":
                        scores = jnp.einsum(
                            "bqd,bkd->bqk",
                            qf.astype(jnp.float32),
                            kf.astype(jnp.float32),
                        ) * scale
                        col = jnp.arange(capacity)[None, None, :]
                        scores = jnp.where(
                            col < kv_len[:, None, None], scores, -jnp.inf
                        )
                        probs = jax.nn.softmax(scores, axis=-1)
                        ctxf = jnp.einsum(
                            "bqk,bkd->bqd", probs, vf.astype(jnp.float32)
                        ).astype(cfg.dtype)
                    else:
                        from rocm_apex_tpu.ops.flash_attention import (
                            flash_attention_decode,
                        )

                        ctxf = flash_attention_decode(qf, kf, vf, kv_len, scale)
            else:
                # prefill: slots start empty (lengths == 0), so causal
                # attention over the fresh window IS the full history —
                # the cache is written but not read
                kf = k.transpose(0, 2, 1, 3).reshape(b * nh_local, sq, hd)
                vf = v.transpose(0, 2, 1, 3).reshape(b * nh_local, sq, hd)
                if cfg.attention_impl == "jnp":
                    scores = jnp.einsum(
                        "bqd,bkd->bqk",
                        qf.astype(jnp.float32),
                        kf.astype(jnp.float32),
                    ) * scale
                    mask = ~jnp.tril(jnp.ones((sq, sq), bool))
                    scores = jnp.where(mask, -jnp.inf, scores)
                    probs = jax.nn.softmax(scores, axis=-1)
                    ctxf = jnp.einsum(
                        "bqk,bkd->bqd", probs, vf.astype(jnp.float32)
                    ).astype(cfg.dtype)
                else:
                    ctxf = flash_attention(qf, kf, vf, None, True, scale)
            ctx = (
                ctxf.reshape(b, nh_local, sq, hd)
                .transpose(0, 2, 1, 3)
                .reshape(b, sq, nh_local * hd)
            )
        elif will_pack:
            pk_causal = self.attn_mask_type == "causal"
            if qkv_bias is None:
                # use_bias=False projection: the unbiased packed ops
                from rocm_apex_tpu.ops.flash_attention import (
                    flash_attention_qkv,
                    flash_attention_qkv_dropout,
                )

                if use_flash_dropout:
                    ctx = flash_attention_qkv_dropout(
                        qkv, _dropout_seed(), cfg.attention_dropout,
                        pk_causal, scale,
                    )
                else:
                    ctx = flash_attention_qkv(qkv, pk_causal, scale)
            elif use_flash_dropout:
                from rocm_apex_tpu.ops.flash_attention import (
                    flash_attention_qkv_bias_dropout,
                )

                ctx = flash_attention_qkv_bias_dropout(
                    qkv, qkv_bias, _dropout_seed(),
                    cfg.attention_dropout, pk_causal, scale,
                )
            else:
                from rocm_apex_tpu.ops.flash_attention import (
                    flash_attention_qkv_bias,
                )

                ctx = flash_attention_qkv_bias(
                    qkv, qkv_bias, pk_causal, scale
                )
        elif use_flash:
            q, k, v = jnp.split(qkv, 3, axis=-1)  # (b, sq, nh, hd)
            qf = q.transpose(0, 2, 1, 3).reshape(b * nh_local, sq, hd)
            kf = k.transpose(0, 2, 1, 3).reshape(b * nh_local, sq, hd)
            vf = v.transpose(0, 2, 1, 3).reshape(b * nh_local, sq, hd)
            if self.attn_mask_type == "causal":
                if cfg.context_parallel_axis is not None:
                    from rocm_apex_tpu.transformer.context_parallel import (
                        ring_flash_attention,
                    )

                    ctxf = ring_flash_attention(
                        qf, kf, vf, cfg.context_parallel_axis,
                        causal=True, scale=scale,
                    )
                elif use_flash_dropout:
                    from rocm_apex_tpu.ops.flash_attention import (
                        flash_attention_dropout,
                    )

                    ctxf = flash_attention_dropout(
                        qf, kf, vf, None, _dropout_seed(),
                        cfg.attention_dropout, True, scale,
                    )
                else:
                    ctxf = flash_attention(qf, kf, vf, None, True, scale)
            else:
                if attention_mask is None:
                    # no padded positions: FULL bidirectional — the
                    # dense kernels need no bias tensor
                    fb = None
                else:
                    # broadcastable (b|1, 1, sq|1, sk) True = masked ->
                    # additive (b, sq, sk)
                    fb = jnp.where(
                        jnp.broadcast_to(attention_mask, (b, 1, sq, sq)),
                        -1e30,
                        0.0,
                    ).astype(jnp.float32)[:, 0]
                # fb is a constant padding mask: no dbias kernel
                if use_flash_dropout:
                    from rocm_apex_tpu.ops.flash_attention import (
                        flash_attention_dropout,
                    )

                    ctxf = flash_attention_dropout(
                        qf, kf, vf, fb, _dropout_seed(),
                        cfg.attention_dropout, False, scale,
                    )
                else:
                    ctxf = flash_attention(
                        qf, kf, vf, fb, False, scale, compute_dbias=False
                    )
            ctx = (
                ctxf.reshape(b, nh_local, sq, hd)
                .transpose(0, 2, 1, 3)
                .reshape(b, sq, nh_local * hd)
            )
        else:
            q, k, v = jnp.split(qkv, 3, axis=-1)  # (b, sq, nh, hd)
            scores = jnp.einsum(
                "bqnd,bknd->bnqk", q, k, preferred_element_type=jnp.float32
            )
            if self.attn_mask_type == "causal":
                if use_pallas_softmax:
                    probs = scaled_upper_triang_masked_softmax(
                        scores.reshape(b * nh_local, sq, sq), scale
                    ).reshape(b, nh_local, sq, sq)
                else:
                    mask = ~jnp.tril(jnp.ones((sq, sq), bool))
                    s = jnp.where(mask, -jnp.inf, scores * scale)
                    probs = jax.nn.softmax(s, axis=-1)
            else:
                if attention_mask is None:
                    # no padded positions: plain softmax — no all-False
                    # mask tensor to materialize
                    probs = jax.nn.softmax(scores * scale, axis=-1)
                else:
                    mask = jnp.broadcast_to(
                        attention_mask, (b, 1, sq, scores.shape[-1])
                    )
                    if use_pallas_softmax:
                        probs = scaled_masked_softmax(scores, mask, scale)
                    else:
                        s = jnp.where(mask, -jnp.inf, scores * scale)
                        probs = jax.nn.softmax(s, axis=-1)
            probs = probs.astype(cfg.dtype)

            if cfg.attention_dropout > 0.0:
                # The reference forks the model-parallel RNG for attention
                # dropout (get_cuda_rng_tracker().fork(), standalone_gpt.py);
                # the probs are TP-sharded over heads, so the tensor rank
                # must be folded in or every rank draws the same mask.
                probs = _Dropout(
                    cfg.attention_dropout,
                    tp_axis=cfg.tensor_axis if tp > 1 else None,
                )(probs, deterministic=deterministic)

            ctx = jnp.einsum(
                "bnqk,bknd->bqnd", probs, v, preferred_element_type=cfg.dtype
            )
            ctx = ctx.reshape(b, sq, nh_local * hd)
        y, _ = RowParallelLinear(
            cfg.hidden_size,
            cfg.hidden_size,
            input_is_parallel=True,
            init_method=_scaled_init(cfg),
            params_dtype=cfg.params_dtype,
            dtype=cfg.dtype,
            world_size=cfg.tensor_parallel_size,
            axis_name=cfg.tensor_axis,
            name="dense",
            **_sp_kwargs(cfg, tp),
        )(ctx)
        if adapters is not None:
            y = apply_lora(
                y, ctx, adapters["dense"], adapters["ids"],
                adapters["active"],
            )
        if cache is not None:
            return y, new_kv
        return y


class ParallelTransformerLayer(nn.Module):
    """Pre-LN transformer block (reference: standalone_gpt.py:575-710):
    LN → attention → residual, LN → MLP → residual, with the
    `apply_residual_connection_post_layernorm` variant.

    ``delta``/``chain``: on the pre-LN path every residual add can
    fuse into a LayerNorm kernel — including the inter-layer one, if
    the caller CHAINS layers by carrying the pending MLP delta instead
    of adding it eagerly. With ``chain=True`` the layer accepts the
    previous layer's pending delta (hidden state = x + delta, the add
    fused into ln1) and returns ``(stream, pending_delta)`` for the
    next layer; `ParallelTransformer` resolves the final pending delta
    inside the final LayerNorm. Measured: the standalone inter-layer
    adds ran at ~1/3 of the Pallas kernels' bandwidth. The default
    (delta=None, chain=False) is the plain x→y contract the pipeline
    stage functions rely on."""

    cfg: GPTConfig
    attn_mask_type: str = "causal"

    @nn.compact
    def __call__(
        self,
        x,
        attention_mask=None,
        deterministic: bool = True,
        delta=None,
        chain: bool = False,
        cache=None,
        chunk=None,
        adapters=None,
    ):
        cfg = self.cfg
        if (delta is not None or chain) and (
            cfg.apply_residual_connection_post_layernorm
        ):
            raise ValueError(
                "residual chaining requires the pre-LN variant"
            )
        if cache is not None and (delta is not None or chain):
            raise ValueError(
                "KV-cached inference does not use residual chaining"
            )
        # on TPU, hidden dropout rides the residual-LN kernels: the
        # producing site hands its delta UNdropped to the consuming LN
        # (ln2 for attention output; the next ln1 / final LN for the
        # chained MLP delta), which drops it in-kernel
        ln_drop = _use_ln_dropout(cfg, deterministic)
        ln1_mod = MixedFusedLayerNorm(
            cfg.hidden_size, eps=cfg.layernorm_epsilon,
            grad_sync_axis=_ln_sync_axis(cfg), name="input_layernorm"
        )
        if delta is None:
            ln1 = ln1_mod(x)
        elif ln_drop:
            # the incoming chained delta is the previous layer's raw
            # MLP output: its hidden dropout happens here
            ln1, x = ln1_mod(
                delta.astype(x.dtype), residual=x,
                dropout_rate=cfg.hidden_dropout,
                dropout_seed=_hidden_dropout_seed(self, cfg),
            )
        else:
            # the previous layer's pending MLP delta joins the stream
            # inside the LN kernel
            ln1, x = ln1_mod(delta.astype(x.dtype), residual=x)
        attn = ParallelAttention(cfg, self.attn_mask_type, name="self_attention")(
            ln1, attention_mask, deterministic, cache, chunk,
            adapters=adapters,
        )
        new_kv = None
        if cache is not None:
            attn, new_kv = attn
        _sow_rms(self, cfg, "attn_out", attn)
        if cfg.hidden_dropout > 0.0 and not ln_drop:
            attn = _hidden_dropout_mod(cfg)(
                attn, deterministic=deterministic
            )
        ln2_mod = MixedFusedLayerNorm(
            cfg.hidden_size,
            eps=cfg.layernorm_epsilon,
            grad_sync_axis=_ln_sync_axis(cfg),
            name="post_attention_layernorm",
        )
        if cfg.apply_residual_connection_post_layernorm:
            residual = ln1
            x = residual + attn.astype(residual.dtype)
            ln2 = ln2_mod(x)
        elif ln_drop:
            ln2, x = ln2_mod(
                attn.astype(x.dtype), residual=x,
                dropout_rate=cfg.hidden_dropout,
                dropout_seed=_hidden_dropout_seed(self, cfg),
            )
        else:
            # pre-LN: the residual add fuses into the LN kernel (the
            # standalone add is a pure HBM round trip otherwise)
            ln2, x = ln2_mod(attn.astype(x.dtype), residual=x)
        mlp = ParallelMLP(cfg, name="mlp")(ln2, deterministic)
        _sow_rms(self, cfg, "mlp_out", mlp)
        if cfg.hidden_dropout > 0.0 and not (ln_drop and chain):
            # unchained exits add the delta eagerly (no LN kernel to
            # ride), so the MLP dropout stays standalone there
            mlp = _hidden_dropout_mod(cfg)(
                mlp, deterministic=deterministic
            )
        if chain:
            return x.astype(cfg.dtype), mlp.astype(cfg.dtype)
        residual = ln2 if cfg.apply_residual_connection_post_layernorm else x
        out = (residual + mlp.astype(residual.dtype)).astype(cfg.dtype)
        if cache is not None:
            return out, new_kv
        return out


class ParallelTransformer(nn.Module):
    """A stack of identical layers (reference: standalone_gpt.py:711-996),
    ended by a final LayerNorm. ``num_layers`` defaults to the config's;
    pipeline users build one stack per stage with
    ``num_layers = cfg.num_layers // pp`` (parallel_state.get_num_layers).
    """

    cfg: GPTConfig
    num_layers: Optional[int] = None
    attn_mask_type: str = "causal"
    post_layer_norm: bool = True

    @nn.compact
    def __call__(
        self,
        x,
        attention_mask=None,
        deterministic: bool = True,
        cache=None,
        chunk=None,
        adapters=None,
    ):
        n = self.num_layers or self.cfg.num_layers
        if adapters is not None and cache is None:
            raise ValueError(
                "adapters= is a KV-cached serving feature; pass cache="
            )
        layer_cls = ParallelTransformerLayer
        # remat is a training memory feature; cached inference never
        # differentiates, so it skips the rematerialized layer class
        if self.cfg.checkpoint_activations and cache is None:
            layer_cls = nn.remat(
                ParallelTransformerLayer, static_argnums=(3, 5)
            )
        # pre-LN stacks chain the pending MLP delta between layers so
        # EVERY residual add fuses into a LayerNorm kernel (see
        # ParallelTransformerLayer); the post-LN variant keeps the
        # eager adds its residual wiring requires. Under activation
        # checkpointing the chain would carry TWO [b, s, h] residuals
        # per remat boundary instead of one — the bandwidth win is not
        # worth doubling the memory that mode exists to save. Cached
        # decode keeps the plain x→y contract (one token: the adds are
        # negligible next to the cache-bound attention reads).
        chain = (
            n > 0
            and not self.cfg.apply_residual_connection_post_layernorm
            and not self.cfg.checkpoint_activations
            and cache is None
        )
        delta = None
        new_k, new_v = [], []
        new_ks, new_vs = [], []
        chunk_k, chunk_v = [], []  # speculative chunk: per-layer (kq, vq)
        # paged caches (inference/paging.py PagedKVCache — duck-typed:
        # this module never imports it) route the per-layer view with a
        # 4th element carrying the page table / page size / int8 scales
        cache_paged = (
            cache is not None
            and getattr(cache, "page_table", None) is not None
        )
        for i in range(n):
            if cache is not None:
                layer_cache = (cache.k[i], cache.v[i], cache.lengths)
                if cache_paged:
                    layer_cache = layer_cache + (dict(
                        page_table=cache.page_table,
                        page_size=cache.page_size,
                        k_scale=(
                            None if cache.k_scale is None
                            else cache.k_scale[i]
                        ),
                        v_scale=(
                            None if cache.v_scale is None
                            else cache.v_scale[i]
                        ),
                    ),)
                layer_adapters = None
                if adapters is not None:
                    # per-layer (P, h, r)/(P, r, o) pool slices; ids
                    # and the pure-base skip flag are shared across
                    # the stack (computed once per apply)
                    layer_adapters = {
                        "qkv": (
                            adapters["qkv"][0][i], adapters["qkv"][1][i]
                        ),
                        "dense": (
                            adapters["dense"][0][i],
                            adapters["dense"][1][i],
                        ),
                        "ids": adapters["ids"],
                        "active": adapters["active"],
                    }
                x, kv_i = layer_cls(
                    self.cfg, self.attn_mask_type, name=f"layer_{i}"
                )(
                    x, attention_mask, deterministic, None, False,
                    layer_cache, chunk, adapters=layer_adapters,
                )
                if chunk is not None and len(chunk) == 3:
                    # speculative chunk: each layer's trailing (kq, vq)
                    # is the packed chunk K/V for the host-side commit
                    chunk_k.append(kv_i[-2])
                    chunk_v.append(kv_i[-1])
                    kv_i = kv_i[:-2]
                new_k.append(kv_i[0])
                new_v.append(kv_i[1])
                if len(kv_i) > 2:  # quantized paged: updated scales
                    new_ks.append(kv_i[2])
                    new_vs.append(kv_i[3])
                continue
            out = layer_cls(
                self.cfg, self.attn_mask_type, name=f"layer_{i}"
            )(x, attention_mask, deterministic, delta, chain)
            if chain:
                x, delta = out
            else:
                x = out
        # chained stacks hand the LAST layer's raw MLP delta to the
        # final LN, which applies its hidden dropout in-kernel (the
        # same contract the inter-layer ln1 consumers follow)
        ln_drop = chain and _use_ln_dropout(self.cfg, deterministic)
        if self.post_layer_norm:
            lnf = MixedFusedLayerNorm(
                self.cfg.hidden_size,
                eps=self.cfg.layernorm_epsilon,
                grad_sync_axis=_ln_sync_axis(self.cfg),
                name="final_layernorm",
            )
            if chain and ln_drop:
                x, _ = lnf(
                    delta.astype(x.dtype), residual=x,
                    dropout_rate=self.cfg.hidden_dropout,
                    dropout_seed=_hidden_dropout_seed(self, self.cfg),
                )
            elif chain:
                x, _ = lnf(delta.astype(x.dtype), residual=x)
            else:
                x = lnf(x)
        elif chain:
            if ln_drop:
                # no final LN to ride: the pending delta's dropout
                # falls back to the standalone path
                delta = _hidden_dropout_mod(self.cfg)(
                    delta, deterministic=deterministic
                )
            x = x + delta.astype(x.dtype)
        x = x.astype(self.cfg.dtype)
        if cache is not None:
            repl = dict(k=tuple(new_k), v=tuple(new_v))
            if new_ks:
                repl.update(
                    k_scale=tuple(new_ks), v_scale=tuple(new_vs)
                )
            if chunk is not None:
                # chunked prefill: tokens landed at explicit per-slot
                # offsets, a variable number per slot — the ENGINE
                # commits the new cursors once per tick (lengths are
                # untouched here)
                if len(chunk) == 3:
                    return x, cache.replace(**repl), (
                        tuple(chunk_k), tuple(chunk_v)
                    )
                return x, cache.replace(**repl)
            # every layer wrote at the same offsets; advance ONCE, for
            # all slots (the engine masks inactive slots afterwards).
            # capacity via the cache protocol: a paged pool's k[0] is
            # (num_pages, heads, page_size, hd), not per-slot rows
            return x, cache.replace(
                lengths=jnp.minimum(
                    cache.lengths + x.shape[1],
                    getattr(cache, "capacity", None)
                    or cache.k[0].shape[1],
                ),
                **repl,
            )
        return x


class TransformerEmbedding(nn.Module):
    """Word (vocab-parallel) + learned position embeddings + dropout
    (reference: standalone_gpt.py:998-1146). ``attend`` projects hidden
    states back onto the vocabulary with the tied word-embedding table.
    """

    cfg: GPTConfig

    def setup(self):
        cfg = self.cfg
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size,
            cfg.hidden_size,
            init_method=_init(cfg),
            params_dtype=cfg.params_dtype,
            dtype=cfg.dtype,
            world_size=cfg.tensor_parallel_size,
            axis_name=cfg.tensor_axis,
            name="word_embeddings",
        )
        self.position_embeddings = self.param(
            "position_embeddings",
            _init(cfg),
            (cfg.max_position_embeddings, cfg.hidden_size),
            cfg.params_dtype,
        )
        self.dropout = _hidden_dropout_mod(cfg)

    def __call__(self, tokens, position_ids=None, deterministic: bool = True):
        cfg = self.cfg
        words = self.word_embeddings(tokens)
        if position_ids is None:
            position_ids = jnp.arange(tokens.shape[1])[None, :]
            if cfg.context_parallel_axis is not None:
                # local shard of the sequence: offset by the shard start
                start = (
                    jax.lax.axis_index(cfg.context_parallel_axis)
                    * tokens.shape[1]
                )
                position_ids = position_ids + start
        pos = jnp.take(self.position_embeddings, position_ids, axis=0).astype(
            cfg.dtype
        )
        x = words + pos
        if _sp_active(cfg, _resolve_tp(cfg)):
            # sequence-parallel region entry: scatter BEFORE dropout so
            # the mask (and everything downstream until the LM-head
            # gather) holds 1/tp of the rows
            x = scatter_to_sequence_parallel_region(
                x, cfg.tensor_axis, dim=1
            )
        if cfg.hidden_dropout > 0.0:
            x = self.dropout(x, deterministic=deterministic)
        return x

    def attend(self, hidden):
        return self.word_embeddings.attend(hidden)

    def attend_loss(self, hidden, labels, loss_mask=None, reduction=None):
        """Tied-head projection fused with CE: logits never materialize
        (`VocabParallelEmbedding.attend_loss`); smoothing/ignore_index
        come from the config."""
        cfg = self.cfg
        return self.word_embeddings.attend_loss(
            hidden, labels, loss_mask, reduction,
            cfg.label_smoothing, cfg.ignore_index, cfg.lm_head_chunk_size,
        )


class GPTModel(nn.Module):
    """Embedding → transformer → tied vocab-parallel LM head
    (reference: standalone_gpt.py:1147-1504 TransformerLanguageModel +
    post_language_model_processing).

    Returns vocab-parallel logits ``(b, s, vocab/tp)``; pair with
    `vocab_parallel_cross_entropy` (or `gpt_loss_fn`). With
    ``labels is not None`` returns per-token losses instead, matching the
    reference's GPT forward — by default through the chunked fused
    linear+CE head (``cfg.fused_lm_head``, ops/linear_xentropy.py),
    which never materializes the ``(b·s, vocab)`` logits.
    ``loss_reduction="mean"`` additionally folds the
    `gpt_loss_fn`-style masked mean INTO the fused op, making the loss
    cotangent a scalar so dx/dW finish inside the forward pass — train
    steps should prefer it.

    ``cfg.sequence_parallel``: the embedding scatters the sequence
    over the tensor axis and the stack runs on ``(b, s/tp, h)``
    shards; the one full-sequence activation is the LM-head input,
    gathered here at the region exit. ``cfg.collective_matmul``
    additionally fuses every TP-edge collective into a ppermute-ring
    matmul (ops/collective_matmul.py) — see docs/parallel.md.

    ``cache`` opens the inference path: pass a KV cache pytree
    (``.k``/``.v`` per-layer buffer tuples + ``.lengths``, the protocol
    of `rocm_apex_tpu.inference.KVCache` — duck-typed so this module
    never imports the inference package) and the call returns
    ``(logits, updated_cache)``. Position ids default to each slot's
    current length; ``tokens`` of width 1 run the single-token decode
    kernel against the cache, wider windows are whole-prompt prefill
    (slots must start at length 0). The caller masks which slots'
    length advances (see inference/engine.py).

    ``chunk=(slot_ids, positions)`` selects CHUNKED prefill instead:
    ``tokens`` is a ``(1, budget)`` packed stream mixing pieces of one
    or more prompts; each layer scatters the chunk's K/V at per-token
    ``(slot, position)`` cache destinations and every token attends
    its slot's rows ``[0, pos + 1)`` (cache prefix + intra-chunk
    causality — the segments kernel merged with a chunk-width bounded
    cache read on the flash path). ``lengths`` are NOT advanced (the
    serving engine commits cursors once per tick); padding tokens
    carry slot id == num_slots. See docs/inference.md.

    ``chunk=(slot_ids, positions, commit_slots)`` — the 3-tuple form —
    runs the SPECULATIVE chunk: attention follows ``slot_ids`` as
    before, but the K/V scatter routes through ``commit_slots``
    (speculative rows carry the ``num_slots`` sentinel there, so
    their K/V never commits in-trace), each slot's cache read is
    bounded by its ``lengths`` entry, and the call returns
    ``(logits, cache, (chunk_k, chunk_v))`` where the extra element
    holds each layer's packed chunk K/V for the engine's
    post-verification accepted-prefix commit. See
    docs/inference.md#speculative-decoding.
    """

    cfg: GPTConfig

    def setup(self):
        cfg = self.cfg
        self.embedding = TransformerEmbedding(cfg, name="embedding")
        self.transformer = ParallelTransformer(cfg, name="transformer")

    def __call__(
        self,
        tokens,
        position_ids=None,
        labels=None,
        loss_mask=None,
        deterministic: bool = True,
        cache=None,
        chunk=None,
        loss_reduction: Optional[str] = None,
        adapters=None,
    ):
        if adapters is not None and cache is None:
            raise ValueError(
                "adapters= is a KV-cached serving feature; pass cache="
            )
        if chunk is not None and cache is None:
            raise ValueError(
                "chunked prefill writes into a KV cache; pass cache= "
                "alongside chunk="
            )
        if cache is not None:
            if labels is not None:
                raise ValueError(
                    "KV-cached inference returns logits; pass labels "
                    "only on the training path"
                )
            if self.cfg.sequence_parallel and chunk is None:
                raise ValueError(
                    "sequence_parallel composes with KV-cached inference "
                    "only on the packed chunk path (pass chunk=, or use "
                    "a model config with sequence_parallel=False for "
                    "decode/prefill applies)"
                )
            if position_ids is None:
                if chunk is not None:
                    # packed chunk: every token carries its own
                    # absolute position (its slot's prefill cursor +
                    # offset within the chunk)
                    position_ids = chunk[1][None, :]
                else:
                    # each slot's window continues at its own length
                    position_ids = (
                        cache.lengths[:, None]
                        + jnp.arange(tokens.shape[1])[None, :]
                    )
            x = self.embedding(tokens, position_ids, deterministic)
            out = self.transformer(
                x, deterministic=deterministic, cache=cache, chunk=chunk,
                adapters=adapters,
            )
            sp_exit = _sp_active(self.cfg, _resolve_tp(self.cfg))
            if chunk is not None and len(chunk) == 3:
                # speculative chunk: also return the per-layer packed
                # chunk K/V (tuple of k, tuple of v) for the host-side
                # accepted-prefix commit
                x, cache, chunk_kv = out
                if sp_exit:
                    x = gather_from_sequence_parallel_region(
                        x, self.cfg.tensor_axis, dim=1,
                        tensor_parallel_output_grad=False,
                    )
                return self.embedding.attend(x), cache, chunk_kv
            x, cache = out
            if sp_exit:
                # sequence-parallel chunk exit: the residual stream is
                # seq-sharded (1, budget/tp, h); the vocab head needs
                # full rows (vocab sharded over the SAME tensor axis)
                x = gather_from_sequence_parallel_region(
                    x, self.cfg.tensor_axis, dim=1,
                    tensor_parallel_output_grad=False,
                )
            return self.embedding.attend(x), cache
        x = self.embedding(tokens, position_ids, deterministic)
        x = self.transformer(x, deterministic=deterministic)
        _sow_rms(self, self.cfg, "hidden_out", x)
        if _sp_active(self.cfg, _resolve_tp(self.cfg)):
            # sequence-parallel region exit: the LM head needs full
            # rows (the vocab is sharded over the SAME tensor axis, so
            # a rank cannot score its local rows against remote vocab
            # shards). This is the one full-sequence activation of the
            # step — everything between embedding scatter and here ran
            # on 1/tp of the rows. tensor_parallel_output_grad=False:
            # the head's internal vjp already psums the hidden grad, so
            # the cotangent here is full and replicated — the backward
            # takes this rank's slice.
            x = gather_from_sequence_parallel_region(
                x, self.cfg.tensor_axis, dim=1,
                tensor_parallel_output_grad=False,
            )
        if labels is None:
            # Tied head: project with the word-embedding table.
            return self.embedding.attend(x)
        cfg = self.cfg
        if loss_reduction not in (None, "mean"):
            raise ValueError(f"unknown loss_reduction {loss_reduction!r}")
        if cfg.fused_lm_head:
            # chunked fused head: the (b·s, vocab) logits/dlogits never
            # materialize; with loss_reduction="mean" the gradients
            # finish inside the forward pass (the train fast path)
            with jax.named_scope("lm_head_loss"):
                if loss_reduction == "mean":
                    return self.embedding.attend_loss(
                        x, labels, loss_mask, "mean"
                    )
                losses = self.embedding.attend_loss(x, labels)
            if loss_mask is not None:
                losses = losses * loss_mask
            return losses
        tp = cfg.tensor_parallel_size
        if tp is None and parallel_state.model_parallel_is_initialized():
            tp = parallel_state.get_tensor_model_parallel_world_size()
        # materialized head: logits stay in compute dtype; the CE
        # kernel upcasts per-tile in VMEM, so casting here would
        # materialize a (b*s, vocab) fp32 copy in HBM (measured
        # ~12 ms/step on the 134M bench: 2.1 GB fwd convert + 2.1 GB
        # fp32 dlogits)
        with jax.named_scope("lm_head_loss"):
            logits = self.embedding.attend(x)
            if (tp or 1) > 1:
                if cfg.label_smoothing or cfg.ignore_index is not None:
                    raise ValueError(
                        "label_smoothing/ignore_index with tp>1 require "
                        "fused_lm_head=True (vocab_parallel_cross_entropy "
                        "has no smoothing/padding support)"
                    )
                losses = vocab_parallel_cross_entropy(
                    logits, labels, cfg.tensor_axis
                )
            else:
                losses = _serial_cross_entropy(
                    logits, labels, cfg.label_smoothing, cfg.ignore_index
                )
        if loss_reduction == "mean":
            return gpt_loss_fn(losses, loss_mask)
        if loss_mask is not None:
            losses = losses * loss_mask
        return losses


def _serial_cross_entropy(logits, labels, smoothing=0.0, padding_idx=None):
    """Fused Pallas CE on the (b*s, vocab) view — avoids materializing
    fp32 logits + log-softmax over the vocabulary. The MATERIALIZED
    head's loss: the logits tensor already exists; prefer the chunked
    fused head (`GPTConfig.fused_lm_head` / ops/linear_xentropy.py),
    which never builds it."""
    b, s, v = logits.shape
    # _fused: differentiation emits dlogits during the forward read of
    # the logits (one pass); the backward is a scalar multiply XLA
    # fuses into the head's dW/dx matmul prologues
    losses = softmax_cross_entropy_loss_fused(
        logits.reshape(b * s, v), labels.reshape(b * s), smoothing,
        padding_idx,
    )
    return losses.reshape(b, s)


def gpt_loss_fn(losses, loss_mask=None):
    """Mean per-token loss (reference loss_func in the GPT tests)."""
    if loss_mask is not None:
        return jnp.sum(losses * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1)
    return jnp.mean(losses)


def gpt_pipeline_functions(cfg: GPTConfig):
    """(embedding, layer, pre_fn, stage_fn, loss_fn) for the pipeline
    schedules.

    The full GPT split the way the reference's build_model does
    (schedules/common.py:18-106): embedding on the entry stage
    (``pre_fn``), a uniform `ParallelTransformerLayer` as the stage
    body, and the tied LM head + CE as the extra-aware ``loss_fn`` on
    the exit stage. Use with
    `forward_backward_pipelining_without_interleaving(stage_fn, loss_fn,
    stacked_layer_params, tokens_microbatched, labels_microbatched,
    extra_params=embedding_params, pre_fn=pre_fn)`.
    """
    embedding = TransformerEmbedding(cfg)
    layer = ParallelTransformerLayer(cfg)

    def pre_fn(extra, tokens):
        # under cfg.sequence_parallel the embedding scatters the
        # sequence before returning, so every stage (and the p2p hops
        # between them) carries the 1/tp shard
        return embedding.apply(extra, tokens)

    def stage_fn(stage_params, x):
        return layer.apply(stage_params, x)

    def loss_fn(extra, hidden, labels):
        # parallel_state-aware tp: the embedding pre_fn resolves it the
        # same way, so scatter and gather can never disagree
        tp = _resolve_tp(cfg)
        if _sp_active(cfg, tp):
            # exit stage: gather the sequence shard before the head —
            # the vocab-parallel head scores full rows against the
            # local vocab shard, over the SAME tensor axis
            hidden = gather_from_sequence_parallel_region(
                hidden, cfg.tensor_axis, dim=1,
                tensor_parallel_output_grad=False,
            )
        if hidden.shape[:2] != labels.shape[:2]:
            raise ValueError(
                f"pipeline exit stage: hidden rows {hidden.shape[:2]} "
                f"!= labels rows {tuple(labels.shape[:2])}. With "
                "sequence_parallel the exit stage must receive the "
                "1/tp sequence SHARD and gather it before the head; a "
                "mismatch here means the stages and the loss disagree "
                "about which axis shards the sequence (e.g. the stack "
                "was built with a different tensor_parallel_size, or "
                "the sequence axis collides with another mesh axis)"
            )
        if cfg.fused_lm_head:
            # the exit stage gets the same fused treatment as
            # GPT.__call__: per-chunk logits only, and the dW of the
            # tied table flows into the embedding (extra) grad through
            # the op's custom VJP. The mean reduction makes the serial
            # variant's gradients finish in its forward pass.
            return embedding.apply(
                extra, hidden, labels, None, "mean",
                method=TransformerEmbedding.attend_loss,
            )
        logits = embedding.apply(
            extra, hidden, method=TransformerEmbedding.attend
        )
        # compute-dtype logits: both CE paths upcast internally per
        # tile (no fp32 logits copy in HBM)
        if tp > 1:
            losses = vocab_parallel_cross_entropy(
                logits, labels, cfg.tensor_axis
            )
        else:
            losses = _serial_cross_entropy(
                logits, labels, cfg.label_smoothing, cfg.ignore_index
            )
        return jnp.mean(losses)

    return embedding, layer, pre_fn, stage_fn, loss_fn
