"""RNN cells + stacked/bidirectional drivers over `lax.scan`.

Reference: apex/RNN/RNNBackend.py — `RNNCell:232` (fused gate matmul
per step), `stackedRNN:90` (layer stack with inter-layer dropout),
`bidirectionalRNN:25` (fwd + reversed cells, concatenated outputs);
mLSTM cell from apex/RNN/cells.py:84. The python-loop time dimension
becomes `lax.scan` — the compiled, remat-friendly TPU form.

Layout: (seq, batch, feature), matching the reference.
"""

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["RNNCellModule", "StackedRNN", "BidirectionalRNN", "CELLS"]


def _rnn_relu(x, h, params):
    new_h = jax.nn.relu(x @ params["w_ih"] + h[0] @ params["w_hh"] + params["b"])
    return (new_h,), new_h


def _rnn_tanh(x, h, params):
    new_h = jnp.tanh(x @ params["w_ih"] + h[0] @ params["w_hh"] + params["b"])
    return (new_h,), new_h


def _lstm(x, h, params):
    hx, cx = h
    gates = x @ params["w_ih"] + hx @ params["w_hh"] + params["b"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    cy = f * cx + i * g
    hy = o * jnp.tanh(cy)
    return (hy, cy), hy


def _gru(x, h, params):
    hx = h[0]
    ri = x @ params["w_ih"] + params["b"]
    rh = hx @ params["w_hh"]
    ir, iz, in_ = jnp.split(ri, 3, axis=-1)
    hr, hz, hn = jnp.split(rh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(in_ + r * hn)
    hy = (1.0 - z) * n + z * hx
    return (hy,), hy


def _mlstm(x, h, params):
    """Multiplicative LSTM (reference cells.py:84): m = (x W_mx)*(h W_mh)
    feeds the gate block in place of h."""
    hx, cx = h
    m = (x @ params["w_mx"]) * (hx @ params["w_mh"])
    gates = x @ params["w_ih"] + m @ params["w_hh"] + params["b"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    cy = f * cx + i * g
    hy = o * jnp.tanh(cy)
    return (hy, cy), hy


#         step fn,   gate multiple, n hidden states, extra params
CELLS = {
    "RNNReLU": (_rnn_relu, 1, 1, ()),
    "RNNTanh": (_rnn_tanh, 1, 1, ()),
    "LSTM": (_lstm, 4, 2, ()),
    "GRU": (_gru, 3, 1, ()),
    "mLSTM": (_mlstm, 4, 2, ("w_mx", "w_mh")),
}


class RNNCellModule(nn.Module):
    """One recurrent layer scanned over time
    (reference RNNBackend.py:232-303)."""

    cell: str
    hidden_size: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, xs, h0: Optional[Tuple] = None, reverse: bool = False):
        step, mult, n_state, extras = CELLS[self.cell]
        in_f = xs.shape[-1]
        hs = self.hidden_size
        params = {
            "w_ih": self.param(
                "w_ih", nn.initializers.lecun_normal(), (in_f, mult * hs),
                self.dtype,
            ),
            "w_hh": self.param(
                "w_hh", nn.initializers.orthogonal(), (hs, mult * hs),
                self.dtype,
            ),
            "b": self.param(
                "b", nn.initializers.zeros_init(), (mult * hs,), self.dtype
            ),
        }
        for name in extras:
            params[name] = self.param(
                name, nn.initializers.lecun_normal(),
                (in_f if name == "w_mx" else hs, hs), self.dtype,
            )
        b = xs.shape[1]
        if h0 is None:
            h0 = tuple(
                jnp.zeros((b, hs), self.dtype) for _ in range(n_state)
            )

        def scan_step(carry, x):
            new_carry, y = step(x, carry, params)
            return new_carry, y

        hN, ys = jax.lax.scan(scan_step, h0, xs, reverse=reverse)
        return ys, hN


class StackedRNN(nn.Module):
    """Layer stack with inter-layer dropout
    (reference RNNBackend.py:90-230)."""

    cell: str
    hidden_size: int
    num_layers: int = 1
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, xs, deterministic: bool = True):
        states = []
        for i in range(self.num_layers):
            xs, hN = RNNCellModule(
                self.cell, self.hidden_size, self.dtype, name=f"layer_{i}"
            )(xs)
            states.append(hN)
            if self.dropout > 0.0 and i < self.num_layers - 1:
                xs = nn.Dropout(rate=self.dropout)(
                    xs, deterministic=deterministic
                )
        return xs, states


class BidirectionalRNN(nn.Module):
    """Forward + reversed cells, outputs concatenated
    (reference RNNBackend.py:25-88)."""

    cell: str
    hidden_size: int
    num_layers: int = 1
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, xs, deterministic: bool = True):
        states = []
        for i in range(self.num_layers):
            fwd, h_f = RNNCellModule(
                self.cell, self.hidden_size, self.dtype, name=f"fwd_{i}"
            )(xs)
            bwd, h_b = RNNCellModule(
                self.cell, self.hidden_size, self.dtype, name=f"bwd_{i}"
            )(xs, reverse=True)
            xs = jnp.concatenate([fwd, bwd], axis=-1)
            states.append((h_f, h_b))
            if self.dropout > 0.0 and i < self.num_layers - 1:
                xs = nn.Dropout(rate=self.dropout)(
                    xs, deterministic=deterministic
                )
        return xs, states
