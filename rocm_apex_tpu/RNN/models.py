"""RNN factories (reference: apex/RNN/models.py:19-47)."""

from rocm_apex_tpu.RNN.backend import BidirectionalRNN, StackedRNN

__all__ = ["RNN", "LSTM", "GRU", "mLSTM"]


def _make(cell):
    def factory(
        input_size,
        hidden_size,
        num_layers=1,
        bidirectional=False,
        dropout=0.0,
        **kw,
    ):
        del input_size  # inferred from the input (flax convention)
        cls = BidirectionalRNN if bidirectional else StackedRNN
        return cls(
            cell=cell,
            hidden_size=hidden_size,
            num_layers=num_layers,
            dropout=dropout,
            **kw,
        )

    factory.__name__ = cell
    return factory


def RNN(input_size, hidden_size, num_layers=1, bidirectional=False,
        dropout=0.0, nonlinearity="tanh", **kw):
    """reference models.py:30-38 (nonlinearity picks the cell)."""
    cell = {"tanh": "RNNTanh", "relu": "RNNReLU"}[nonlinearity]
    return _make(cell)(
        input_size, hidden_size, num_layers, bidirectional, dropout, **kw
    )


LSTM = _make("LSTM")
GRU = _make("GRU")
mLSTM = _make("mLSTM")
