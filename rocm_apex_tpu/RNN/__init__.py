"""RNN stack (deprecated in the reference; kept for parity).

Reference: apex/RNN/ — models.py:19-47 factories (RNN/LSTM/GRU/mLSTM),
RNNBackend.py (bidirectionalRNN:25, stackedRNN:90, RNNCell:232),
cells.py:84 (mLSTM). The reference marks the package deprecated; this
rebuild expresses the recurrences as `lax.scan` (the XLA-friendly form)
under the same factory API.
"""

from rocm_apex_tpu.RNN.models import GRU, LSTM, RNN, mLSTM  # noqa: F401
from rocm_apex_tpu.RNN.backend import (  # noqa: F401
    BidirectionalRNN,
    RNNCellModule,
    StackedRNN,
)

__all__ = [
    "RNN",
    "LSTM",
    "GRU",
    "mLSTM",
    "StackedRNN",
    "BidirectionalRNN",
    "RNNCellModule",
]
