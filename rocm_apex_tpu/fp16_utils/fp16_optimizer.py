"""FP16_Optimizer: the legacy master-weight optimizer wrapper.

Reference: apex/fp16_utils/fp16_optimizer.py:13-554 — wraps any
optimizer with fp32 master weights, static/dynamic loss scaling, and
overflow-skipped steps. Functional restatement over the modern pieces
(the reference itself points users to amp):

    opt = FP16_Optimizer(optax_tx, static_loss_scale=128.0)
    state = opt.init(model_params_fp16)
    ...
    scaled_loss = opt.scale_loss(loss, state)         # backward on this
    state = opt.step(state, grads_fp16)               # skips on overflow
    model_params = state.model_params
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from rocm_apex_tpu.amp.scaler import LossScaler as _Scaler
from rocm_apex_tpu.amp.scaler import ScalerState, all_finite
from rocm_apex_tpu.optimizers._common import tree_where

__all__ = ["FP16_Optimizer"]


class FP16OptimizerState(NamedTuple):
    model_params: Any   # low-precision tree
    master_params: Any  # fp32 tree
    inner_state: Any
    scaler_state: ScalerState


class FP16_Optimizer:
    """Reference constructor vocabulary (fp16_optimizer.py:13-90):
    exactly one of static_loss_scale / dynamic_loss_scale."""

    def __init__(
        self,
        tx: optax.GradientTransformation,
        static_loss_scale: float = 1.0,
        dynamic_loss_scale: bool = False,
        dynamic_loss_args: Optional[dict] = None,
        verbose: bool = False,
    ):
        self.tx = tx
        if dynamic_loss_scale:
            args = dynamic_loss_args or {}
            self.scaler = _Scaler(
                loss_scale="dynamic",
                init_scale=args.get("init_scale", 2.0**32),
                scale_factor=args.get("scale_factor", 2.0),
                scale_window=args.get("scale_window", 1000),
            )
        else:
            self.scaler = _Scaler(loss_scale=float(static_loss_scale))
        self.verbose = verbose

    def init(self, model_params: Any) -> FP16OptimizerState:
        masters = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32), model_params
        )
        return FP16OptimizerState(
            model_params=model_params,
            master_params=masters,
            inner_state=self.tx.init(masters),
            scaler_state=self.scaler.init(),
        )

    def scale_loss(self, loss, state: FP16OptimizerState):
        """The `backward(loss)` scaling half (reference
        fp16_optimizer.py backward); differentiate the scaled loss."""
        return self.scaler.scale(state.scaler_state, loss)

    def step(self, state: FP16OptimizerState, grads: Any) -> FP16OptimizerState:
        """Unscale, overflow-check, inner update on masters, cast-down
        (reference fp16_optimizer.py step: skip on overflow)."""
        grads, found_inf = self.scaler.unscale(state.scaler_state, grads)
        new_scaler, skip = self.scaler.update(state.scaler_state, found_inf)
        safe = jax.tree_util.tree_map(
            lambda g: jnp.where(jnp.isfinite(g), g, 0.0), grads
        )
        updates, new_inner = self.tx.update(
            safe, state.inner_state, state.master_params
        )
        new_masters = optax.apply_updates(state.master_params, updates)
        new_masters = tree_where(skip, state.master_params, new_masters)
        new_inner = tree_where(skip, state.inner_state, new_inner)
        new_model = jax.tree_util.tree_map(
            lambda mo, ma: ma.astype(mo.dtype),
            state.model_params,
            new_masters,
        )
        return FP16OptimizerState(
            model_params=new_model,
            master_params=new_masters,
            inner_state=new_inner,
            scaler_state=new_scaler,
        )

    # reference helpers
    @staticmethod
    def has_overflow(grads):
        return ~all_finite(grads)
