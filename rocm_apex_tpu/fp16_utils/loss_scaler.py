"""Legacy static/dynamic loss scalers.

Reference: apex/fp16_utils/loss_scaler.py — `LossScaler:10` (static)
and `DynamicLossScaler:47` (2x growth / 2x backoff with a growth
window). Thin shims over the amp scaler with the legacy constructor
vocabulary (scale_factor, scale_window).
"""

import jax.numpy as jnp

from rocm_apex_tpu.amp.scaler import LossScaler as _AmpScaler
from rocm_apex_tpu.amp.scaler import ScalerState, all_finite

__all__ = ["LossScaler", "DynamicLossScaler"]


class LossScaler(_AmpScaler):
    """Static scaler (reference loss_scaler.py:10-44)."""

    def __init__(self, scale: float = 1.0):
        super().__init__(loss_scale=float(scale))

    # legacy helpers (the reference exposes these names)
    @staticmethod
    def has_overflow(grads) -> jnp.ndarray:
        return ~all_finite(grads)

    def update_scale_legacy(self, state: ScalerState, overflow):
        state, _ = self.update(state, overflow)
        return state


class DynamicLossScaler(_AmpScaler):
    """Dynamic scaler (reference loss_scaler.py:47-119)."""

    def __init__(
        self,
        init_scale: float = 2.0**32,
        scale_factor: float = 2.0,
        scale_window: int = 1000,
    ):
        super().__init__(
            loss_scale="dynamic",
            init_scale=init_scale,
            scale_factor=scale_factor,
            scale_window=scale_window,
        )

    has_overflow = staticmethod(LossScaler.has_overflow)
