"""Manual precision-conversion helpers.

Reference: apex/fp16_utils/fp16util.py — `network_to_half:35` (cast all
floating params to half), `convert_network:60` / `BN_convert_float`
(cast but keep batch-norm fp32), `prep_param_lists:90` (model params +
fp32 master copies), `master_params_to_model_params:136` /
`model_grads_to_master_grads:162`. Pytree-functional equivalents; the
batch-norm exemption uses the same path heuristic as amp
(utils/tree.py is_batchnorm_path).
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from rocm_apex_tpu.utils.tree import is_batchnorm_path, tree_cast

__all__ = [
    "network_to_half",
    "convert_network",
    "BN_convert_float",
    "prep_param_lists",
    "master_params_to_model_params",
    "model_grads_to_master_grads",
]


def network_to_half(params: Any, dtype=jnp.float16) -> Any:
    """Cast every floating leaf to half (reference fp16util.py:35-44)."""
    return tree_cast(params, dtype)


def convert_network(params: Any, dtype=jnp.float16) -> Any:
    """Cast to half but keep batch-norm leaves fp32
    (reference fp16util.py:60-74)."""
    return tree_cast(params, dtype, keep_fp32_predicate=is_batchnorm_path)


def BN_convert_float(params: Any) -> Any:
    """Cast batch-norm leaves back to fp32 (reference fp16util.py:46-57)."""

    def one(path, leaf):
        if is_batchnorm_path(path) and jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(jnp.float32)
        return leaf

    return jax.tree_util.tree_map_with_path(one, params)


def prep_param_lists(params: Any) -> Tuple[Any, Any]:
    """(model_params, fp32_master_copies)
    (reference fp16util.py:90-133; the flat-tensor variant collapses to
    the same pytree here — packing is the optimizer's concern)."""
    masters = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32), params
    )
    return params, masters


def master_params_to_model_params(model_params: Any, master_params: Any) -> Any:
    """Copy master values into the model tree's dtypes
    (reference fp16util.py:136-160)."""
    return jax.tree_util.tree_map(
        lambda mo, ma: ma.astype(mo.dtype), model_params, master_params
    )


def model_grads_to_master_grads(model_grads: Any) -> Any:
    """fp32 copies of low-precision grads (reference fp16util.py:162-175)."""
    return jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32), model_grads
    )
