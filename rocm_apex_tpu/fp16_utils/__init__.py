"""Legacy manual mixed-precision helpers (fp16_utils).

Reference: apex/fp16_utils/ — fp16util.py (network_to_half:35,
convert_network:60, prep_param_lists:90, grad/master copies :136-175),
fp16_optimizer.py (FP16_Optimizer:13), loss_scaler.py (LossScaler:10,
DynamicLossScaler:47). The reference deprecates these in favor of amp
(docs/source/fp16_utils.rst); here they are thin functional shims over
the same machinery amp uses, kept for capability parity.
"""

from rocm_apex_tpu.fp16_utils.fp16util import (  # noqa: F401
    BN_convert_float,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
)
from rocm_apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer  # noqa: F401
from rocm_apex_tpu.fp16_utils.loss_scaler import (  # noqa: F401
    DynamicLossScaler,
    LossScaler,
)

__all__ = [
    "network_to_half",
    "convert_network",
    "BN_convert_float",
    "prep_param_lists",
    "master_params_to_model_params",
    "model_grads_to_master_grads",
    "FP16_Optimizer",
    "LossScaler",
    "DynamicLossScaler",
]
