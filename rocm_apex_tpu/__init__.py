"""rocm_apex_tpu — a TPU-native training-utilities framework.

Brand-new JAX/XLA/Pallas implementation of the capabilities of Apex
(reference: abhinavvishnu/rocm-apex): automatic mixed precision with
O0–O5 policy levels and dynamic loss scaling, fused optimizers, fused
layers (LayerNorm, scaled-masked softmax, dense/MLP, attention,
softmax-cross-entropy, sync/group batch norm), data-parallel gradient
reduction, and Megatron-style tensor/pipeline parallelism — all
redesigned TPU-first:

* precision is a functional *policy* threaded through modules instead of
  monkey-patched op registries (reference: apex/amp/amp.py:75-198);
* the kernel layer is Pallas/Mosaic instead of CUDA/HIP (reference:
  csrc/, apex/contrib/csrc/);
* the communication backend is XLA collectives (psum / all_gather /
  ppermute / psum_scatter) over `jax.sharding.Mesh` axes instead of
  NCCL/RCCL process groups (reference: apex/parallel/distributed.py).

Subpackage map (mirrors the reference's public surface, SURVEY.md §1):

    amp             precision policies O0–O5 + loss scaling
    optimizers      fused Adam/LAMB/SGD/NovoGrad/Adagrad (+ mixed-precision LAMB)
    normalization   FusedLayerNorm / MixedFusedLayerNorm
    mlp, fused_dense fused dense/MLP modules
    parallel        DistributedDataParallel-equivalent, SyncBatchNorm, LARC
    transformer     parallel_state ("mpu"), tensor_parallel, pipeline_parallel
    contrib         xentropy, flash/fused attention, transducer, ASP sparsity,
                    group BN, ZeRO-style distributed optimizers
    ops             the Pallas kernel layer (shared by everything above)
    models          flax reference models (ResNet, DCGAN, GPT, BERT)
    inference       serving tier (beyond the reference): KV cache,
                    single-token decode kernel, sampling,
                    continuous-batching engine
"""

import logging as _logging

__version__ = "0.1.0"


class _RankInfoFormatter(_logging.Formatter):
    """Rank-aware log formatter.

    Injects the (tp, pp, dp) rank triple into every record, mirroring the
    reference's RankInfoFormatter (reference: apex/__init__.py:31-45,
    apex/transformer/parallel_state.py:169). On a single-controller JAX
    program ranks come from the active parallel_state mesh if initialized.
    """

    def format(self, record):
        from rocm_apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            record.rank_info = parallel_state.get_rank_info()
        else:
            record.rank_info = "(-, -, -)"
        return super().format(record)


def _get_logger():
    logger = _logging.getLogger(__name__)
    if not logger.handlers:
        handler = _logging.StreamHandler()
        handler.setFormatter(
            _RankInfoFormatter(
                "%(asctime)s - PID:%(process)d - rank:%(rank_info)s - %(name)s - %(levelname)s - %(message)s"
            )
        )
        logger.addHandler(handler)
        logger.propagate = False
    return logger


logger = _get_logger()


# Lazy subpackage access (PEP 562): `import rocm_apex_tpu` then
# `rocm_apex_tpu.amp` works like the reference's `import apex` →
# `apex.amp` (apex/__init__.py imports them eagerly; lazy here keeps
# the base import free of jax-graph construction).
_SUBPACKAGES = {
    "amp", "optimizers", "parallel", "transformer", "normalization",
    "mlp", "fused_dense", "fp16_utils", "RNN", "reparameterization",
    "contrib", "models", "ops", "profiler", "checkpoint",
    "multi_tensor_apply", "utils",
}


def __getattr__(name):
    if name in _SUBPACKAGES:
        import importlib

        module = importlib.import_module(f"{__name__}.{name}")
        globals()[name] = module
        return module
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | _SUBPACKAGES)
