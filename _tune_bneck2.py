"""Dev driver: dissect the conv3x3 fwd kernel cost at the l1 shape by
ablating taps / masks / prologue / stats.

Usage: python _tune_bneck2.py
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, H, W, C = 128, 56, 56, 64
HW, PTOT = H * W, N * H * W
LO = 64
BP = 2048
ITERS = 30


def scan_time(make_step, init):
    def run(n):
        @jax.jit
        def f(c):
            return jax.lax.scan(lambda c, _: (make_step(c), None),
                                c, None, length=n)[0]
        return f

    f1, f2 = run(ITERS), run(2 * ITERS)
    for f in (f1, f2):
        r = f(init)
        float(jax.tree_util.tree_leaves(r)[0].reshape(-1)[0]
              .astype(jnp.float32))

    def best(f):
        ts = []
        for _ in range(2):
            t0 = time.perf_counter()
            r = f(init)
            float(jax.tree_util.tree_leaves(r)[0].reshape(-1)[0]
                  .astype(jnp.float32))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    return max(best(f2) - best(f1), 1e-9) / ITERS * 1000


def make(taps, masks, prologue, stats, fp32fin=False):
    offs = [dy * W + dx for dy in (-1, 0, 1) for dx in (-1, 0, 1)][:taps]

    def kern(xp, xm, xn, a, b, w_ref, y_ref, s1_ref, s2_ref):
        j = pl.program_id(0)
        p0 = j * BP
        u = jnp.concatenate([xp[...], xm[...], xn[...]], axis=0)
        if prologue:
            s = u.astype(jnp.float32) * a[...] + b[...]
            u = jnp.maximum(s, 0.0).astype(u.dtype)
        acc = None
        for t, off in enumerate(offs):
            tap = u[LO + off: LO + off + BP]
            if masks:
                p = p0 + jax.lax.broadcasted_iota(jnp.int32, (BP, 1), 0)
                q = p + off
                valid = (q >= 0) & (q // HW == p // HW)
                dx = (t % 3) - 1
                col = p % W
                if dx < 0:
                    valid &= col >= 1
                elif dx > 0:
                    valid &= col <= W - 2
                tap = jnp.where(valid, tap, jnp.zeros_like(tap))
            d = jax.lax.dot_general(
                tap, w_ref[t], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc = d if acc is None else acc + d
        y_ref[...] = acc.astype(y_ref.dtype)
        if stats:
            @pl.when(j == 0)
            def _():
                s1_ref[...] = jnp.zeros_like(s1_ref)
                s2_ref[...] = jnp.zeros_like(s2_ref)
            s1_ref[...] += jnp.sum(acc, axis=0, keepdims=True)
            s2_ref[...] += jnp.sum(acc * acc, axis=0, keepdims=True)
        else:
            s1_ref[...] = jnp.zeros_like(s1_ref)
            s2_ref[...] = jnp.zeros_like(s2_ref)

    k = BP // LO
    last = PTOT // LO - 1
    specs = [
        pl.BlockSpec((LO, C), lambda j: (jnp.maximum(j * k - 1, 0), 0)),
        pl.BlockSpec((BP, C), lambda j: (j, 0)),
        pl.BlockSpec((LO, C), lambda j: (jnp.minimum((j + 1) * k, last), 0)),
        pl.BlockSpec((1, C), lambda j: (0, 0)),
        pl.BlockSpec((1, C), lambda j: (0, 0)),
        pl.BlockSpec((9, C, C), lambda j: (0, 0, 0)),
    ]
    return pl.pallas_call(
        kern,
        grid=(PTOT // BP,),
        in_specs=specs,
        out_specs=[
            pl.BlockSpec((BP, C), lambda j: (j, 0)),
            pl.BlockSpec((1, C), lambda j: (0, 0)),
            pl.BlockSpec((1, C), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((PTOT, C), jnp.bfloat16),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
    )


def main():
    key = jax.random.PRNGKey(0)
    x = (jax.random.normal(key, (PTOT, C)) * 0.5).astype(jnp.bfloat16)
    a = jnp.ones((1, C), jnp.float32)
    b = jnp.zeros((1, C), jnp.float32)
    w = (jax.random.normal(key, (9, C, C)) * 0.05).astype(jnp.bfloat16)
    gbmap = PTOT * C * 2 / 1e9

    cases = [
        ("full (9 taps, masks, prologue, stats)", (9, True, True, True)),
        ("no masks", (9, False, True, True)),
        ("no prologue", (9, True, False, True)),
        ("no stats", (9, True, True, False)),
        ("1 tap only", (1, True, True, True)),
        ("3 taps", (3, True, True, True)),
        ("bare (1 tap, nothing)", (1, False, False, False)),
    ]
    for name, cfg in cases:
        call = make(*cfg)

        def step(x):
            y, s1, s2 = call(x, x[:LO], x[:LO], a, b, w)[0:3] if False else \
                call(x[:LO], x, x[:LO], a, b, w)
            return x + (y[0, :1] * 1e-30 + s1[0, :1].astype(jnp.bfloat16)
                        * 0).astype(x.dtype)

        # correct operand order: (prev, main, next)
        def step(x):
            y, s1, s2 = call(x, x, x, a, b, w)
            return x + (y[0, :1] * 1e-30).astype(x.dtype)

        t = scan_time(step, x)
        print(f"{name:40s} {t:7.3f} ms ({2*gbmap/(t/1e3):5.0f} GB/s)",
              flush=True)


if __name__ == "__main__":
    main()
