"""Driver benchmark: one JSON line on stdout.

Measures the flagship config on whatever single chip is available: a
Megatron-style GPT train step under the O5/amp-O2 recipe — bf16 model
params computing with Pallas flash attention + the chunked fused
linear+CE LM head (ops/linear_xentropy.py: the (b·s, vocab) logits
never materialize; `--loss=naive` A/Bs the materialized fp32-logits
optax path, and the stderr line reports the head's share of the step
from a standalone fwd+bwd timing of the same op), fp32 masters
updated by the XLA-tree-fused mixed-precision Adam (optimizers/mixed.py
— see its header for why tree fusion, not buffer packing, is the TPU
fast path), dynamic loss scaling with jit-safe skip-step — reporting
tokens/sec/chip.

The DEFAULT is the TRAINING configuration (dropout 0.1 — attention
dropout in-kernel in the flash kernels, hidden dropout in-kernel in the
residual-LN kernels): the config users train is the config the driver
gate records (round-5 change; `--dropout=0` measures the eval-shaped
config under the un-suffixed metric key).

`--seq-parallel` A/Bs the tp-axis configuration: the model shards over
ALL visible chips on the tensor axis with sequence-parallel
activations between the TP boundaries (GPTConfig.sequence_parallel);
`--collective-matmul` additionally decomposes the boundary collectives
into ppermute-ring matmuls (ops/collective_matmul.py). These emit
`_sp_tpN` / `_spcm_tpN`-suffixed metric keys so the tp-axis step-time
series stays separate from the dp bench above.

`--audit` (gpt bench) additionally prints a static program audit of
one train step to stderr — collective counts/bytes + dot FLOPs from
`rocm_apex_tpu.monitor.audit` (trace-only, no timing impact) — and
emits the estimated per-step collective wire bytes as a
`gpt_comm_payload_mib` jsonl metric.

`--comm-dtype=int8` (gpt bench) quantizes the ring-collective hop
payloads to int8 with fp32 scale sidecars
(ops/quantized_collectives.py): with `--dist-opt` the ZeRO grad
reduce-scatter and param all-gather rings, with `--collective-matmul`
the TP-boundary rings. The `--dist-opt` bench always emits
`gpt_comm_payload_mib` (audit-traced, ~3.5-4x lower at int8) next to
the step-time line; docs/perf.md has the A/B numbers.

`python bench.py serve` measures the SERVING path: the continuous-
batching engine's chunked-prefill token-budget scheduler on a mixed
prompt-length workload, reporting `gpt_serve_tokens_per_sec_per_chip`
and `gpt_serve_ttft_ms` (p95) with the whole-prompt prefill A/B run in
the same invocation as the baseline ratio (docs/inference.md).

Timing notes:
* ITERS steps run inside ONE dispatch via `lax.scan` — the axon tunnel
  adds tens of ms of per-dispatch latency that real multi-step training
  does not pay;
* on the tunnel platform `block_until_ready` does NOT synchronize; the
  timed region ends with a scalar value fetch.

The reference publishes no numbers (SURVEY.md §6, BASELINE.json
"published": {}), so ``vs_baseline`` is the ratio against BASELINE.md's
north-star bar (70% MFU): vs_baseline = MFU / 0.70 for the model
benches (gpt / rn50 / bert). The micro-bench subcommands report a
different, per-metric efficiency ratio named on their stderr line:
attn = fraction of bf16 peak FLOP/s, ln = xla_ms / pallas_ms
(speedup), optim = bandwidth_floor_ms / measured_ms.
"""

import sys
import time

import jax
import jax.numpy as jnp

from rocm_apex_tpu import monitor
from rocm_apex_tpu.amp import LossScaler
from rocm_apex_tpu.models.gpt import GPTConfig, GPTModel
from rocm_apex_tpu.monitor import peak_flops_per_chip  # noqa: F401 - re-export
from rocm_apex_tpu.optimizers.mixed import MixedPrecisionAdam

BATCH = 16
SEQ = 1024
# one warmup runN (compile + state settle) then one timed. 50 steps per
# dispatch: the axon tunnel's value-fetch round-trip is ~100 ms, so at
# N steps the wall clock over-reports each step by ~100/N ms — real
# training fetches nothing per step.
ITERS = 50


def _dropout_rng0(dropout: float, on_tpu: bool):
    # dropout keys use the TPU hardware RNG ('rbg'): threefry mask
    # generation is VPU-expensive (measured as most of the dropout-on
    # step overhead — BASELINE.md round-4 rows); rbg is the TPU-native
    # PRNG for exactly this
    if dropout > 0.0 and on_tpu:
        return jax.random.key(2, impl="rbg")
    return jax.random.PRNGKey(2)


# the driver's stdout contract rides the shared observability sink: one
# MetricsLogger with a JsonlWriter on stdout, records passed through
# verbatim (monitor/logger.py `emit`) so the BENCH_*.json comparisons
# stay byte-for-byte valid
_REPORT_LOGGER = monitor.MetricsLogger(
    writers=[monitor.JsonlWriter(stream=sys.stdout)], memory_stats=False
)


def _report(metric, value, unit, vs_baseline, extra=""):
    print(extra, file=sys.stderr)
    _REPORT_LOGGER.emit(
        {
            "metric": metric,
            # sub-10 values keep 4 decimals (a 0.168 ms kernel must
            # not be published as 0.2)
            "value": round(value, 1) if value >= 10 else round(value, 4),
            "unit": unit,
            "vs_baseline": round(vs_baseline, 4),
        }
    )


def bench_rn50(fused: bool = False):
    """BASELINE.json config 2: ResNet-50, O5 recipe (bf16 + fp32
    masters via amp.initialize) + FusedAdam, images/sec/chip.
    DDP-equivalent gradient psum degenerates on one chip (the
    multi-chip path is exercised by tests/L0/test_parallel.py).
    `--fused=1` routes the 13 stride-1 blocks through the fused Pallas
    bottleneck kernels (ops/fused_bottleneck.py) and reports under a
    `_fused`-suffixed key; the default XLA chain remains the headline
    because Mosaic's shifted-tap conv lowering measures well below
    XLA's native conv emitter at RN50 channel widths (BASELINE.md
    round-4 fused-bottleneck section has the kernel-level numbers)."""
    import optax

    from rocm_apex_tpu import amp, models
    from rocm_apex_tpu.optimizers import FusedAdam

    on_tpu = jax.default_backend() == "tpu"
    batch = 128 if on_tpu else 4  # b128 beats b64 by 16% img/s on v5e
    size = 224 if on_tpu else 32
    iters = 20 if on_tpu else 2
    # the policy's compute dtype threads through the model definition
    # (SURVEY §7: flax-style dtype IS the O-level cast_model_type);
    # without it every conv and feature map runs fp32 — measured 97.7
    # vs 53.1 ms per step on v5e. BN params stay fp32 via amp.initialize
    # (keep_batchnorm_fp32) and flax accumulates BN stats in fp32.
    model = models.resnet50(
        num_classes=1000,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        fused=fused and on_tpu,
    )
    x0 = jnp.zeros((batch, size, size, 3))
    variables = model.init(jax.random.PRNGKey(0), x0)
    params, batch_stats = variables["params"], variables["batch_stats"]
    optimizer = FusedAdam(1e-3, weight_decay=1e-4)
    params, optimizer, amp_state = amp.initialize(
        params, optimizer, opt_level="O5" if on_tpu else "O0"
    )
    opt_state = optimizer.init(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, size, size, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)

    def one_step(carry, _):
        params, batch_stats, opt_state, scaler_states = carry
        st = amp_state.replace(scaler_states=scaler_states)

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats},
                x.astype(jnp.bfloat16 if on_tpu else jnp.float32),
                mutable=["batch_stats"],
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y
            ).mean()
            return amp.scale_loss(ce, st), (mut["batch_stats"], ce)

        (_, (bs2, ce)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        grads, found_inf = amp.unscale_grads(grads, st)
        st2, skip = amp.update_scale(st, found_inf)
        updates, opt2 = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params = amp.skip_step(skip, new_params, params)
        opt2 = amp.skip_step(skip, opt2, opt_state)
        return (new_params, bs2, opt2, st2.scaler_states), ce

    @jax.jit
    def runN(params, batch_stats, opt_state, scaler_states):
        carry, ces = jax.lax.scan(
            one_step,
            (params, batch_stats, opt_state, scaler_states),
            None,
            length=iters,
        )
        return carry, ces

    carry, ces = runN(params, batch_stats, opt_state, amp_state.scaler_states)
    float(ces[-1])
    t0 = time.perf_counter()
    carry, ces = runN(*carry)
    loss = float(ces[-1])
    dt = (time.perf_counter() - t0) / iters
    img_s = batch / dt
    # RN50 train ~ 3 x 4.1 GFLOPs fwd per image at 224x224
    # (monitor.resnet50_train_flops — the shared accounting module)
    mfu = monitor.mfu(monitor.resnet50_train_flops(batch), dt)
    # the driver's BASELINE series must never mix configs under one
    # key: the fused-kernel run gets its own metric name
    suffix = "_fused" if (fused and on_tpu) else ""
    _report(
        f"rn50_train_images_per_sec_per_chip{suffix}",
        img_s, "images/s", mfu / 0.70,
        f"rn50: step={dt*1000:.1f}ms loss={loss:.3f} mfu={mfu:.3f}",
    )


def build_bert_train(dropout: float = 0.0, batch: int = 0,
                     remat: bool = False, iters: int = 0):
    """The BERT bench step, importable: used by `bench_bert` AND
    `_profile_bert.py`, so the committed profiles can never drift from
    the benchmark they explain. Returns
    ``(runN, state0, rng0, cfg, batch, seq, params32)``."""
    from rocm_apex_tpu.models import BertConfig, BertModel
    from rocm_apex_tpu.optimizers.mixed import MixedPrecisionLamb
    from rocm_apex_tpu.utils.tree import path_str

    on_tpu = jax.default_backend() == "tpu"
    batch = batch or (8 if on_tpu else 2)
    seq = 512 if on_tpu else 64
    iters = iters or (20 if on_tpu else 2)
    cfg = BertConfig(
        vocab_size=30592 if on_tpu else 1024,
        hidden_size=1024 if on_tpu else 64,
        num_layers=24 if on_tpu else 2,
        num_attention_heads=8 if on_tpu else 4,
        ffn_hidden_size=4096 if on_tpu else 128,
        max_position_embeddings=seq,
        hidden_dropout=dropout,
        attention_dropout=dropout,
        tensor_parallel_size=1,
        checkpoint_activations=remat,
    )
    model = BertModel(cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (batch, seq), 0, cfg.vocab_size
    )
    lm_labels = jnp.roll(tokens, 1, axis=1)
    params32 = model.init(jax.random.PRNGKey(1), tokens[:1])
    flat = jax.tree_util.tree_map_with_path(
        lambda kp, _: not (
            path_str(kp).endswith("bias") or "layernorm" in path_str(kp).lower()
        ),
        params32,
    )
    # store_model=False: the bf16 model copy is cast from the masters
    # in-scan instead of riding the carry — the carried copy would be
    # double-buffered (2 x 0.66 GB), which is exactly the b8 OOM margin
    # on the 16 GB chip
    # bf16 moments: half the m/v traffic and state (the
    # moment_dtype knob, tolerance pinned by
    # test_mixed_precision.py::test_bf16_moments_close_to_fp32);
    # with fp32 moments the b16 config exceeds the 16 GB chip
    opt = MixedPrecisionLamb(
        1e-4, weight_decay=0.01, weight_decay_mask=flat,
        compute_dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        moment_dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        store_model=False,
    )
    state = opt.init(params32)

    def one_step(carry, _):
        state, rng = carry
        rng, step_rng = jax.random.split(rng)

        def loss_fn(p):
            losses, _ = model.apply(
                p, tokens, lm_labels=lm_labels,
                deterministic=dropout == 0.0,
                rngs={"dropout": step_rng} if dropout > 0.0 else None,
            )
            return jnp.mean(losses)

        loss, grads = jax.value_and_grad(loss_fn)(opt.model_params(state))
        state2, _ = opt.step_and_probe(state, grads)
        return (state2, rng), loss

    @jax.jit
    def runN(state, rng):
        carry, losses = jax.lax.scan(
            one_step, (state, rng), None, length=iters
        )
        return carry, losses

    return (
        runN, state, _dropout_rng0(dropout, on_tpu), cfg, batch, seq,
        params32,
    )


def bench_bert(dropout: float = 0.0, batch: int = 0, remat: bool = False):
    """BASELINE.json config 4: BERT-Large-shaped MLM pretrain step with
    the mixed-precision LAMB recipe (bf16 model copy + fp32 masters,
    `MixedPrecisionLamb` — norms fused into the update passes, no
    materialized update buffer) + fused LayerNorm, tokens/sec/chip.
    24L/1024h with head_dim 128 (the TPU-first head shape; see main()).
    ``--batch=16 --remat`` measures the large-batch config with
    per-layer activation checkpointing."""
    on_tpu = jax.default_backend() == "tpu"
    iters = 20 if on_tpu else 2
    runN, state, rng0, cfg, batch, seq, params32 = build_bert_train(
        dropout, batch, remat, iters
    )
    carry, losses = runN(state, rng0)
    float(losses[-1])
    t0 = time.perf_counter()
    carry, losses = runN(*carry)
    loss = float(losses[-1])
    dt = (time.perf_counter() - t0) / iters
    tok_s = batch * seq / dt
    # same Megatron-style crediting as the GPT bench, via the shared
    # monitor.model_flops accounting (+ the tied MLM-head projection
    # trio; see main())
    flops = monitor.model_flops(
        cfg, batch, seq,
        raw_param_count=sum(
            int(x.size) for x in jax.tree_util.tree_leaves(params32)
        ),
    )
    mfu = monitor.mfu(flops, dt)
    # non-default configs get distinct metric names: the driver's
    # BASELINE series must never mix configs under one key
    suffix = "_dropout" if dropout > 0.0 else ""
    if batch != (8 if on_tpu else 2):
        suffix += f"_b{batch}"
    if remat:
        suffix += "_remat"
    _report(
        f"bert_large_train_tokens_per_sec_per_chip{suffix}", tok_s,
        "tokens/s", mfu / 0.70,
        f"bert: step={dt*1000:.1f}ms loss={loss:.3f} mfu={mfu:.3f} "
        f"dropout={dropout} remat={remat}",
    )


def bench_serve(budget: int = 0, whole_prompt: bool = False,
                trace: str = "", paged: bool = False,
                page_size: int = 0, kv_dtype: str = "",
                shared_prefix: bool = False, spec_k: int = -1,
                chaos: int = -1, slo: bool = False,
                metrics_port: int = -1, replicas: int = 0,
                tp: int = 0, disagg: bool = False,
                adapters: int = 0, ranks: str = ""):
    """Serving benchmark: the continuous-batching engine on a MIXED
    prompt-length workload (fixed seed — the raggedness is the point:
    whole-prompt prefill pads every prompt to the longest and stalls
    every decode slot behind each admit; the chunked token-budget
    scheduler streams prompts through the fixed budget while the
    decode grid advances every tick).

    Emits ``gpt_serve_tokens_per_sec_per_chip`` (generated tokens/sec;
    vs_baseline = speedup over the whole-prompt A/B run measured in the
    same invocation) and ``gpt_serve_ttft_ms`` (p95 enqueue→first-token;
    vs_baseline = whole-prompt p95 / chunked p95) through the shared
    MetricsLogger/JsonlWriter stdout contract. ``--whole-prompt``
    instead reports ONLY the legacy path under ``_whole``-suffixed keys
    (its own BASELINE series). ``--budget=N`` overrides the prefill
    token budget (default 256 on TPU, 16 on CPU).

    ``--trace=PATH`` attaches a `monitor.Tracer` to the measured
    engine and writes (a) PATH: Chrome trace-event JSON with one track
    per request (enqueue → queue_wait → prefill_chunk spans → decode →
    finish) plus the engine's mixed/decode tick track — load it in
    Perfetto; and (b) PATH.requests.jsonl: the per-request completion
    records (TTFT, TPOT, tokens, chunks, queue wait) next to the
    aggregate ``stats()``. Tracing is host-side ring-buffer writes on
    timestamps the engine already takes — the compiled programs and
    the one-fetch-per-tick pattern are unchanged.

    ``--paged`` A/Bs the block-table cache against the contiguous
    chunked engine on the same workload: greedy tokens are asserted
    IDENTICAL (the bf16/fp32 paged path is parity-exact), throughput
    reports under ``gpt_serve_tokens_per_sec_per_chip_paged`` with
    vs_baseline = paged/contiguous, and a cache-bytes line contrasts
    the contiguous allocation with the paged pool and its PEAK live
    pages (the memory actually needed). ``--page-size=N`` tunes the
    page (default 16 CPU / 64 TPU); ``--kv-dtype=int8`` stores int8
    pools with per-(page, head) scales (the parity assert relaxes to
    a match-count report; keys gain an ``_int8`` suffix).
    ``--shared-prefix`` switches to the shared-system-prompt workload
    and A/Bs paged+prefix-sharing against plain paged: same tokens,
    ``prefix_hits``/``shared_page_ratio`` > 0, and the TTFT p95 win
    reports under ``gpt_serve_ttft_ms_shared_prefix``.

    ``--chaos=SEED`` runs the mixed workload once under a seeded
    `inference.FaultPlan` (a device-step failure, a NaN-poisoned
    logits row, probabilistic host-fetch failures, a page-allocation
    failure on ``--paged``) with a bounded queue and a mid-run cancel,
    then asserts the ISSUE-12 completion-accounting identity: every
    submitted request yields exactly one completion record —
    completed + shed + quarantined + cancelled + expired ==
    submitted — with the mixed step still traced ONCE and, under
    ``--paged``, every page back in the pool after the drain. Reports
    under ``gpt_serve_chaos_survival`` (vs_baseline = completed
    fraction). Same SEED, same schedule: a failure replays exactly.

    ``--slo`` is the telemetry plane's acceptance rig: the measured
    per-request TTFTs are replayed through a real `monitor.SLOMonitor`
    (latency `SLO` over a ``serve_ttft_ms`` histogram, objective 0.9,
    threshold = 2x the fault-free p95) on an EVENT-INDEX clock — one
    request per tick, so the Google-SRE window math runs over request
    counts and the asserts cannot flake on wall-clock jitter. Alone it
    asserts the fault-free run stays QUIET (zero burn-rate alerts).
    Composed with ``--chaos=SEED`` it first calibrates the threshold
    on a fault-free pass (asserted quiet), then augments the fault
    plan with a burst of retry-backoff device-step faults and asserts
    the TTFT burn-rate alert FIRES. Reports under
    ``gpt_serve_slo_alerts``. ``--metrics-port=N`` stands up the
    telemetry exporter over the measured engine's registry on
    127.0.0.1:N (0 = ephemeral) and self-scrapes ``/metrics`` and
    ``/healthz`` once before exiting.

    ``--replicas=N`` runs the multi-replica fabric
    (`inference.ReplicaRouter`, N >= 2) on the mixed workload and
    reports ``gpt_serve_fleet_tokens_per_sec`` (vs_baseline = fleet
    rate / a single-replica run measured in the same invocation).
    Greedy fleet tokens are asserted bitwise-identical to the
    single-replica reference (placement must never change outputs).
    Composed with ``--chaos=SEED`` the fleet pass runs again under a
    seed-derived replica fault plan (a ``replica_kill`` mid-decode
    plus a ``replica_slow`` latency injection) and asserts the
    ISSUE-15 survival identity: every submitted request accounted
    exactly ONCE, every recovered request's tokens bitwise-identical
    to the undisturbed reference (no token emitted twice), the killed
    replica's pages/slots provably clean after quarantine, each
    replica's mixed step still traced once, and the merged fleet
    registry's TTFT histogram reproducing the combined per-replica
    completion streams. ``--metrics-port=N`` here stands the exporter
    up over the ROUTER (zero-arg merged-registry provider, fleet
    `/healthz`) and self-scrapes it.

    ``--tp=N`` A/Bs the tensor-parallel paged serve at EQUAL CHIP
    COUNT: the same mixed workload runs on a tp=1 engine (1 chip) and
    on a tp=N engine whose params are sliced from the SAME tp=1
    checkpoint (`inference.shard_tp1_params`), each still ONE fused
    mixed trace per tick. Greedy tokens are asserted IDENTICAL and the
    per-chip KV bytes exactly 1/N (the pools shard over heads).
    Reports ``gpt_serve_tokens_per_sec_per_chip_tpN`` (fleet rate / N
    chips; vs_baseline = per-chip ratio over tp=1 — below 1.0 on CPU
    where the simulated mesh buys no real bandwidth, the per-chip KV
    headroom is the win) and ``gpt_serve_ttft_ms_tpN``. Needs N
    visible devices (CPU: ``--xla_force_host_platform_device_count``).

    ``--disagg`` A/Bs disaggregated prefill/decode serving at EQUAL
    CHIP COUNT: a ``replica_classes=["prefill", "decode", ...]`` fleet
    (half prefill, half decode; ``--replicas=N`` sizes it, default 2)
    against an identical-replica fleet on the same workload. Fresh
    prompts chunk on prefill replicas, finished prompts migrate WITH
    their KV pages (page-shipping, no re-prefill) to decode replicas.
    Greedy tokens are asserted IDENTICAL to the uniform fleet, at
    least one handoff must actually ship pages, and both fleets must
    drain leak-free. Reports
    ``gpt_serve_tokens_per_sec_per_chip_disagg`` (vs_baseline =
    disagg / uniform fleet rate) plus per-class TTFT p95 under
    ``gpt_serve_ttft_ms_prefill`` / ``_decode`` (vs_baseline = uniform
    fleet p95 / class p95), attributed to the replica class that
    FINISHED each request — the decode-class line is the
    time-to-first-token the fleet's decode capacity actually delivers.

    ``--adapters=N`` A/Bs batched multi-LoRA serving against the
    single-model engine on the same workload IN ONE INVOCATION: N
    tenant adapters (``--ranks=R1,R2,...`` cycles per-adapter ranks,
    default 2,4,8, rank-padded into one packed `AdapterPool`) are
    striped across the requests next to base traffic, applied as
    segmented gather->bmm deltas inside the ONE fused mixed trace.
    Adapter-0 greedy tokens are asserted bitwise identical to the
    base engine, at least one adapter must visibly change tokens, and
    a park/reclaim churn wave (2N registered adapters over N+1
    residency slots) must neither retrace nor leak refs. Reports
    ``gpt_serve_adapter_tokens_per_sec_per_chip`` (vs_baseline =
    aggregate rate / single-model rate — the ~10% adapter tax
    ceiling). Composed with ``--chaos=SEED`` it runs the
    tenant-isolation scenario instead: a seeded one-tenant burst
    (burster and size derived from SEED) replayed through a real
    `monitor.TenantSLOBoard` on an event-index clock must trip ONLY
    the bursting tenant's TTFT burn-rate monitor — every other
    tenant's monitor stays quiet (structural isolation: each reads
    only its own labeled series) — while the per-tenant
    completion-accounting identity holds exactly. Reports
    ``gpt_serve_tenant_isolation``.

    ``--spec-k=K`` A/Bs speculative decoding (n-gram self-drafting
    through the mixed step, `inference/drafting.py`) against the
    non-speculative chunked engine on a HIGH-ACCEPTANCE workload:
    periodic prompts whose greedy continuations repeat, the regime the
    suffix-matching drafter locks onto. Greedy tokens are asserted
    IDENTICAL (and again on quick paged-bf16 and paged-int8 passes —
    the rollback path must be invisible in tokens on every cache
    layout), throughput reports under
    ``gpt_serve_tokens_per_sec_per_chip_spec{K}`` with vs_baseline =
    spec/non-spec, and the stderr line carries acceptance rate,
    drafted/accepted totals, and TTFT/TPOT p95. ``--spec-k=0`` runs
    only the baseline series."""
    from rocm_apex_tpu.inference import InferenceEngine, SamplingParams

    on_tpu = jax.default_backend() == "tpu"
    req_budget = budget  # pre-default: the spec branch sizes its own
    import numpy as np

    if on_tpu:
        cfg = GPTConfig(
            vocab_size=32768, hidden_size=1024, num_layers=8,
            num_attention_heads=8, max_position_embeddings=1024,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_parallel_size=1,
        )
        num_slots, capacity = 8, 1024
        budget = budget or 256
        lens = [32, 64, 128, 256, 768]
        probs = [0.3, 0.3, 0.2, 0.15, 0.05]
        n_requests, max_new = 32, 64
    else:
        # CPU smoke shape: small model, but a LONG-TAILED prompt mix
        # against a real pad width — the regime the scheduler targets
        # (the whole-prompt path pays b*max_prompt_len, chunked pays
        # the actual prompt tokens)
        cfg = GPTConfig(
            vocab_size=512, hidden_size=128, num_layers=2,
            num_attention_heads=4, max_position_embeddings=160,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_parallel_size=1, attention_impl="jnp",
        )
        num_slots, capacity = 4, 160
        # swept on this workload: 24 -> 1.08x over whole-prompt, 32 ->
        # ~parity, 48 -> ~1.3x (the 96-token tail absorbs in 2 ticks)
        budget = budget or 48
        lens = [8, 16, 32, 96]
        probs = [0.35, 0.3, 0.2, 0.15]
        n_requests, max_new = 12, 6
    model = GPTModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )
    rng = np.random.RandomState(0)

    if spec_k >= 0:
        # ---- speculative-decoding A/B. The workload is periodic on
        # purpose: a tiny greedy model continues a repeating prompt
        # with the same period, so the n-gram drafter's proposals are
        # mostly right and the measured win is the DESIGN's ceiling
        # regime (k accepted tokens per cache sweep). Random-prompt
        # traffic exercises the rollback path instead — covered by the
        # paged parity passes below and the L0 suite.
        # decode-heavy on purpose: speculative decoding amortizes the
        # DECODE tick, so short periodic prompts + a long generation
        # phase isolate the per-token win from prefill fixed costs
        n_req = 16 if on_tpu else 8
        spec_new = 128 if on_tpu else 96
        reps = 8 if on_tpu else 5
        prompts = []
        for i in range(n_req):
            p = 3 + i % 4  # periods 3..6: all hit the 3/2-gram cascade
            cyc = rng.randint(1, cfg.vocab_size, size=p).tolist()
            prompts.append((cyc * (reps + 1))[: p * reps + i % 3])
        # every decoding slot needs k+1 chunk rows per tick for its
        # span (last token + k drafts) — and no more: each extra
        # budget row is dead weight in every spec tick's fused chunk
        sbudget = req_budget or (num_slots * (max(spec_k, 2) + 1))

        def run_spec(k, paged_kv=None, use_paged=False, reqs=None,
                     new_toks=None):
            eng = InferenceEngine(
                model, params, num_slots=num_slots, capacity=capacity,
                sampling=SamplingParams(temperature=0.0), seed=0,
                prefill_token_budget=sbudget, spec_k=k,
                paged=use_paged,
                page_size=(page_size or (64 if on_tpu else 16))
                if use_paged else 16,
                kv_dtype=paged_kv,
            )
            work = reqs if reqs is not None else prompts
            # warmup long enough that accepted spans COMMIT (a span
            # that finishes its request skips the commit program —
            # 3-token warmups would leave that compile in the timed
            # window)
            eng.generate(work[:num_slots], max_new_tokens=10)
            eng.reset_stats()
            t0 = time.perf_counter()
            results = eng.generate(
                work, max_new_tokens=new_toks or spec_new
            )
            dt = time.perf_counter() - t0
            gen = sum(len(r.tokens) for r in results)
            return eng, [r.tokens for r in results], gen / dt, dt

        eng_b, toks_b, rate_b, dt_b = run_spec(0)
        s_b = eng_b.stats()
        tpot_b = [c["tpot_ms"] for c in eng_b.completions]
        print(
            f"serve[spec0]: {rate_b:.1f} gen tok/s over {dt_b:.2f}s "
            f"(budget={sbudget}) ttft p95={s_b['ttft_ms_p95']:.0f}ms "
            f"tpot p95={np.percentile(tpot_b, 95):.1f}ms",
            file=sys.stderr,
        )
        if spec_k == 0:
            _report("gpt_serve_tokens_per_sec_per_chip_spec0", rate_b,
                    "tokens/s", 1.0, "")
            return
        eng_s, toks_s, rate_s, dt_s = run_spec(spec_k)
        # a throughput win that changes tokens is not a win: greedy
        # speculative output must be TOKEN-IDENTICAL to the baseline
        for i, (tb, ts) in enumerate(zip(toks_b, toks_s)):
            assert tb == ts, f"spec-k={spec_k} changed tokens (req {i})"
        s_s = eng_s.stats()
        tpot_s = [c["tpot_ms"] for c in eng_s.completions]
        assert eng_s.mixed_trace_count == 1, (
            f"spec mixed step traced {eng_s.mixed_trace_count}x"
        )
        # quick parity passes on the paged layouts (reduced workload):
        # the accept/rollback walk must be invisible in tokens whether
        # rejected rows would have landed in bf16 or int8 pages
        sub = prompts[: num_slots + 2]
        for kvd in (None, jnp.int8):
            _, pb, _, _ = run_spec(0, paged_kv=kvd, use_paged=True,
                                   reqs=sub, new_toks=12)
            _, ps_, _, _ = run_spec(spec_k, paged_kv=kvd,
                                    use_paged=True, reqs=sub,
                                    new_toks=12)
            name = "int8" if kvd is not None else "bf16"
            assert pb == ps_, (
                f"spec-k={spec_k} changed tokens on the paged {name} "
                f"cache"
            )
        acc = s_s["acceptance_rate"]
        print(
            f"serve[spec{spec_k}]: {rate_s:.1f} gen tok/s over "
            f"{dt_s:.2f}s vs baseline {rate_b:.1f} "
            f"({rate_s / rate_b:.2f}x); acceptance={acc:.2f} "
            f"({s_s['tokens_accepted']:.0f}/"
            f"{s_s['tokens_drafted']:.0f} drafted, "
            f"{s_s['rollbacks']:.0f} rollbacks) "
            f"ttft p95={s_s['ttft_ms_p95']:.0f}ms "
            f"tpot p95={np.percentile(tpot_s, 95):.1f}ms; tokens "
            f"identical (contiguous + paged bf16/int8)",
            file=sys.stderr,
        )
        _report(
            f"gpt_serve_tokens_per_sec_per_chip_spec{spec_k}", rate_s,
            "tokens/s", rate_s / rate_b,
            f"spec-k={spec_k} {rate_s:.1f} vs non-spec {rate_b:.1f} "
            f"tok/s (speedup = vs_baseline); acceptance {acc:.2f}; "
            f"tokens identical on contiguous/paged/int8",
        )
        _report(
            f"gpt_serve_tpot_ms_spec{spec_k}",
            float(np.percentile(tpot_s, 95)), "ms",
            float(np.percentile(tpot_b, 95))
            / max(float(np.percentile(tpot_s, 95)), 1e-9),
            f"tpot p95: spec {np.percentile(tpot_s, 95):.1f} ms vs "
            f"baseline {np.percentile(tpot_b, 95):.1f} ms "
            f"(ratio = vs_baseline); ttft p95 "
            f"{s_s['ttft_ms_p95']:.0f} vs {s_b['ttft_ms_p95']:.0f} ms",
        )
        return
    if shared_prefix:
        # shared-system-prompt traffic (the millions-of-users regime:
        # most tokens of most requests are the same tokens): one fixed
        # prefix + a short random tail per request. The length is NOT
        # page-aligned on purpose: the tail's first tokens land inside
        # the last shared page, so the A/B also exercises the partial
        # borrow -> copy-on-write fork path
        prefix_len = 250 if on_tpu else 60
        prefix = rng.randint(0, cfg.vocab_size, size=prefix_len).tolist()
        prompts = [
            prefix
            + rng.randint(
                0, cfg.vocab_size, size=int(rng.randint(4, 17))
            ).tolist()
            for _ in range(n_requests)
        ]
    else:
        prompts = [
            rng.randint(
                0, cfg.vocab_size, size=int(rng.choice(lens, p=probs))
            ).tolist()
            for _ in range(n_requests)
        ]
    total_prompt = sum(len(p) for p in prompts)

    def build(chunked, tracer=None):
        return InferenceEngine(
            model, params, num_slots=num_slots, capacity=capacity,
            max_prompt_len=max(lens),
            sampling=SamplingParams(temperature=0.0), seed=0,
            prefill_token_budget=budget if chunked else None,
            tracer=tracer,
        )

    def run(chunked, tracer=None):
        # compile warmup on the SAME engine (its jit caches), then a
        # clean telemetry window for the timed pass — greedy decoding
        # is rng-independent, so the warmup does not perturb tokens
        eng = build(chunked, tracer)
        eng.generate(prompts[: num_slots], max_new_tokens=3)
        eng.reset_stats()
        if tracer is not None:
            tracer.clear()  # the timeline starts at the timed window
        t0 = time.perf_counter()
        results = eng.generate(prompts, max_new_tokens=max_new)
        dt = time.perf_counter() - t0
        gen = sum(len(r.tokens) for r in results)
        return eng, results, gen / dt, dt

    def slo_replay_ttft(completions, threshold_ms):
        # replay the measured per-request TTFTs through the real SLO
        # machinery on an EVENT-INDEX clock (one request = one tick):
        # the burn-rate windows count requests, not seconds, so the
        # assert is deterministic while still exercising
        # Histogram.good_below, the window differencing, and the
        # rising-edge alert path end to end
        from rocm_apex_tpu.monitor import BurnRule, MetricRegistry, SLO, SLOMonitor

        reg = MetricRegistry()
        hist = reg.histogram(
            "serve_ttft_ms",
            "Replayed enqueue->first-token latency (ms).",
        )
        mon = SLOMonitor(registry=reg)
        mon.add(SLO(
            "serve_ttft", 0.9, series=hist, threshold=threshold_ms,
            # request-counted windows: any 6-request span burning the
            # 10% error budget at >= 2x, confirmed by its trailing 3,
            # trips the rule
            windows=(BurnRule(6.0, 3.0, 2.0),),
        ))
        mon.tick(now=0.0)  # pre-traffic baseline sample
        # requests shed/cancelled before their first token carry
        # ttft_ms == 0 — no latency was observed, nothing to judge
        ttfts = [
            c["ttft_ms"] for c in completions if c["ttft_ms"] > 0
        ]
        for i, t in enumerate(ttfts):
            hist.observe(t)
            mon.tick(now=float(i + 1))
            mon.alerts(now=float(i + 1))
        return mon

    def scrape_metrics(eng):
        # --metrics-port: stand the exporter up over the measured
        # engine's registry and self-scrape each endpoint once — the
        # bench proves the surface; a deployment would leave it up
        import http.client
        import json as _json

        srv = monitor.start_exporter(
            eng.registry, port=metrics_port, engine=eng
        )
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", srv.port, timeout=10
            )
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == 200 and b"serve_ttft_ms_count" in body, (
                f"/metrics scrape failed: status={resp.status}"
            )
            conn.request("GET", "/healthz")
            hz = conn.getresponse()
            healthy = _json.loads(hz.read()).get("healthy")
            conn.close()
            print(
                f"serve metrics: {srv.url} — /metrics {len(body)} "
                f"bytes, /healthz status={hz.status} healthy={healthy}",
                file=sys.stderr,
            )
        finally:
            srv.close()

    if adapters > 0:
        # ---- batched multi-LoRA serving A/B: N tenant adapters ride
        # the ONE fused mixed chunk+decode program as segmented
        # gather->bmm deltas over rank-padded packed pool buffers
        # (ops/lora.py, inference/adapters.py). The headline is
        # aggregate tok/s staying within ~10% of the single-model
        # engine on the SAME workload — the adapters must be near-free
        # — with adapter-0 greedy tokens asserted bitwise identical to
        # the base engine. Composed with --chaos=SEED it instead runs
        # the tenant-isolation scenario: a seeded one-tenant burst
        # must burn ONLY that tenant's TTFT SLO (every other tenant's
        # monitor on the `TenantSLOBoard` stays quiet) while the
        # per-tenant completion-accounting identity holds.
        from rocm_apex_tpu.inference import AdapterPool

        rank_list = [int(r) for r in ranks.split(",") if r] or [2, 4, 8]
        if any(r < 1 for r in rank_list):
            raise SystemExit(f"--ranks must be >= 1, got {rank_list}")
        max_rank = max(rank_list)
        # widen the A/B window past the serve default (12 req x 6 tok
        # is ~0.1 s on this box — the ratio drowns in scheduler
        # jitter); both sides run the SAME widened workload
        n_req_a = max(n_requests, 4 * (adapters + 1))
        max_new_a = max(max_new, 24)
        prompts_a = [
            prompts[i % len(prompts)] for i in range(n_req_a)
        ]

        def make_pool(max_resident):
            return AdapterPool(
                cfg.num_layers, cfg.hidden_size,
                max_resident=max_resident, max_rank=max_rank,
            )

        def register_all(pool, n, seed0=100, prefix="tenant"):
            # scale 0.5: big enough that a non-base adapter visibly
            # flips greedy argmax (the delta-took-effect canary)
            rng_a = np.random.RandomState(seed0)
            aids = []
            for i in range(n):
                r = rank_list[i % len(rank_list)]
                ws = [
                    {
                        "qkv": (
                            0.5 * rng_a.randn(cfg.hidden_size, r),
                            0.5 * rng_a.randn(r, 3 * cfg.hidden_size),
                        ),
                        "dense": (
                            0.5 * rng_a.randn(cfg.hidden_size, r),
                            0.5 * rng_a.randn(r, cfg.hidden_size),
                        ),
                    }
                    for _ in range(cfg.num_layers)
                ]
                aids.append(pool.register(
                    f"{prefix}-{i}", ws, rank=r, tier=i % 3,
                ))
            return aids

        def build_lora(pool):
            return InferenceEngine(
                model, params, num_slots=num_slots, capacity=capacity,
                max_prompt_len=max(lens),
                sampling=SamplingParams(temperature=0.0), seed=0,
                prefill_token_budget=budget, adapter_pool=pool,
            )

        def submit_and_drain(eng, work, new_tokens, sink=None):
            ids = [
                eng.add_request(p, new_tokens, adapter_id=a)
                for p, a in work
            ]
            out = {}
            while eng.has_work():
                for r in eng.step():
                    out[r.request_id] = r
            if sink is not None:
                sink.update(out)
            return [out[i] for i in ids]

        if chaos >= 0:
            from rocm_apex_tpu.monitor import (
                BurnRule, MetricRegistry, TenantSLOBoard,
            )

            rng_c = np.random.RandomState(chaos)
            pool = make_pool(adapters + 1)
            aids = register_all(pool, adapters)
            burst_aid = aids[int(rng_c.randint(0, len(aids)))]
            burst_n = 4 * num_slots + int(rng_c.randint(0, num_slots))
            burst_tenant = pool.tenant_of(burst_aid)
            eng = build_lora(pool)
            # warmup compiles the lora mixed + decode programs OUTSIDE
            # the measured window (a compile spike inside phase 1
            # would inflate the calibration p95 past any burst)
            submit_and_drain(
                eng,
                list(zip(prompts_a[:num_slots],
                         ([0] + aids)[:num_slots])),
                3,
            )
            eng.reset_stats()
            # phase 1 (calm): every tenant — including the future
            # burster — trickles requests one slot-wave at a time, so
            # queue wait never builds and the TTFTs calibrate the
            # alert threshold
            wave = [
                (prompts_a[i % len(prompts_a)],
                 ([0] + aids)[i % (adapters + 1)])
                for i in range(2 * (adapters + 1))
            ]
            for w0 in range(0, len(wave), num_slots):
                submit_and_drain(eng, wave[w0:w0 + num_slots], max_new)
            calm = [
                c["ttft_ms"] for c in eng.completions
                if c["ttft_ms"] > 0
            ]
            threshold = max(2.0 * float(np.percentile(calm, 95)), 1.0)
            # phase 2 (burst): the seeded tenant dumps burst_n
            # requests at once — the tail queues behind its own
            # burst, so ITS ttft blows through 2x the calm p95 while
            # no other tenant observes a single slow request
            submit_and_drain(
                eng,
                [(prompts_a[j % len(prompts_a)], burst_aid)
                 for j in range(burst_n)],
                max_new,
            )
            assert eng.mixed_trace_count == 1, (
                f"adapter burst retraced the mixed step "
                f"{eng.mixed_trace_count}x"
            )
            pool.assert_consistent()
            assert pool.snapshot()["refs"] == 1, (
                "adapter refs leaked across the burst"
            )
            # per-tenant completion-accounting identity: the host
            # tenant counters sum EXACTLY to the completion records,
            # per tenant and in aggregate
            ts = eng.tenant_stats()
            by_tenant = {}
            for c in eng.completions:
                t = c.get("tenant") or "base"
                by_tenant[t] = by_tenant.get(t, 0) + 1
            assert {
                t: s["completed"] for t, s in ts.items()
            } == by_tenant, (ts, by_tenant)
            assert sum(
                s["generated_tokens"] for s in ts.values()
            ) == sum(c["new_tokens"] for c in eng.completions)
            # replay the measured TTFTs through a real TenantSLOBoard
            # on an event-index clock: one labeled histogram, one
            # monitor per tenant, each reading ONLY its own series
            reg_b = MetricRegistry()
            hist = reg_b.histogram(
                "serve_ttft_ms",
                "Replayed per-tenant enqueue->first-token (ms).",
                labelnames=("tenant",),
            )
            board = TenantSLOBoard(
                hist, objective=0.9, threshold_ms=threshold,
                windows=(BurnRule(6.0, 3.0, 2.0),),
            )
            for t in sorted(by_tenant):
                board.ensure(t)
            board.tick(now=0.0)
            i = 0
            for c in eng.completions:
                if c["ttft_ms"] <= 0:
                    continue
                i += 1
                hist.observe(
                    c["ttft_ms"], tenant=c.get("tenant") or "base"
                )
                board.tick(now=float(i))
                board.alerts(now=float(i))
            fired = {
                t for t, mon in board.monitors.items() if mon.events
            }
            assert burst_tenant in fired, (
                f"{burst_tenant}'s burst did not trip its TTFT "
                f"burn-rate alert (threshold {threshold:.1f} ms)"
            )
            assert fired == {burst_tenant}, (
                f"the burst bled into other tenants' SLOs: "
                f"{sorted(fired - {burst_tenant})} also fired"
            )
            n_alerts = len(board.monitors[burst_tenant].events)
            print(
                f"serve[adapters={adapters} chaos seed={chaos}]: "
                f"tenant {burst_tenant} burst {burst_n} requests, "
                f"{n_alerts} alert(s) at threshold {threshold:.1f} ms; "
                f"{len(by_tenant) - 1} other tenants quiet; "
                f"accounting identity holds "
                f"({len(eng.completions)} records)",
                file=sys.stderr,
            )
            _report(
                "gpt_serve_tenant_isolation", float(n_alerts),
                "alerts", 1.0,
                f"seeded one-tenant burst (seed={chaos}): only "
                f"{burst_tenant}'s burn-rate monitor fired; "
                f"per-tenant completion accounting exact; mixed step "
                f"traced once; adapter pool leak-free",
            )
            if metrics_port >= 0:
                scrape_metrics(eng)
            return

        # ---- throughput A/B: single-model reference first, then the
        # same workload with requests striped across base + N adapters
        def run_base():
            eng = build(True)
            eng.generate(prompts_a[:num_slots], max_new_tokens=3)
            eng.reset_stats()
            t0 = time.perf_counter()
            results = eng.generate(prompts_a, max_new_tokens=max_new_a)
            dt = time.perf_counter() - t0
            gen = sum(len(r.tokens) for r in results)
            return eng, results, gen / dt, dt

        eng_b, res_b, rate_b, dt_b = run_base()
        pool = make_pool(adapters + 1)  # all resident: pure serving
        aids = register_all(pool, adapters)
        assign = [
            ([0] + aids)[i % (adapters + 1)] for i in range(n_req_a)
        ]
        eng_a = build_lora(pool)
        submit_and_drain(
            eng_a,
            list(zip(prompts_a[:num_slots], assign[:num_slots])), 3,
        )
        eng_a.reset_stats()
        t0 = time.perf_counter()
        res_a = submit_and_drain(
            eng_a, list(zip(prompts_a, assign)), max_new_a
        )
        dt_a = time.perf_counter() - t0
        rate_a = sum(len(r.tokens) for r in res_a) / dt_a
        assert eng_a.mixed_trace_count == 1, (
            f"{adapters} adapters traced the mixed step "
            f"{eng_a.mixed_trace_count}x — the segmented delta must "
            f"live inside the ONE program"
        )
        # adapter-0 requests are the base model: bitwise identical
        base_reqs = [i for i, a in enumerate(assign) if a == 0]
        for i in base_reqs:
            assert res_a[i].tokens == res_b[i].tokens, (
                f"adapter-0 request {i} diverged from the base engine"
            )
        assert any(
            res_a[i].tokens != res_b[i].tokens
            for i, a in enumerate(assign) if a != 0
        ), "no adapter changed any tokens — deltas not applied?"
        # park/reclaim churn on the SAME engine: register a second
        # wave of adapters past residency and cycle through them —
        # evictions/revivals must not retrace or leak
        extra = register_all(pool, adapters, seed0=200, prefix="late")
        churn = [aids[-1]] + extra + [aids[0]]
        for aid in churn:
            submit_and_drain(eng_a, [(prompts_a[0], aid)], 2)
        snap = pool.snapshot()
        assert snap["evictions"] > 0, snap
        assert eng_a.mixed_trace_count == 1, (
            "adapter park/reclaim retraced the mixed step"
        )
        pool.assert_consistent()
        assert snap["refs"] == 1, "adapter refs leaked"
        ratio = rate_a / rate_b
        s_a = eng_a.stats()
        print(
            f"serve[adapters={adapters}]: {rate_a:.1f} gen tok/s over "
            f"{dt_a:.2f}s vs single-model {rate_b:.1f} "
            f"({ratio:.2f}x); ranks {rank_list} padded to {max_rank}; "
            f"uploads={int(s_a['adapter_uploads'])} "
            f"evictions={int(s_a['adapter_evictions'])} "
            f"revivals={int(s_a['adapter_revivals'])}; adapter-0 "
            f"tokens bitwise identical ({len(base_reqs)} reqs); "
            f"mixed step traced once across {2 * adapters} adapters "
            f"+ churn",
            file=sys.stderr,
        )
        _report(
            "gpt_serve_adapter_tokens_per_sec_per_chip", rate_a,
            "tokens/s", ratio,
            f"{adapters} concurrent adapters (ranks {rank_list}, "
            f"rank-padded to {max_rank}) vs single-model "
            f"{rate_b:.1f} tok/s (ratio = vs_baseline); one mixed "
            f"trace; adapter-0 bitwise identical to base",
        )
        if metrics_port >= 0:
            scrape_metrics(eng_a)
        return

    if tp >= 2:
        # ---- equal-chip-count tensor-parallel A/B: tp=1 on 1 chip vs
        # tp=N on N chips, SAME checkpoint, SAME workload. The tokens
        # must not move; the per-chip KV footprint must drop 1/N.
        import dataclasses

        from rocm_apex_tpu.inference import shard_tp1_params
        from rocm_apex_tpu.transformer import parallel_state

        if len(jax.devices()) < tp:
            raise SystemExit(
                f"--tp={tp} needs {tp} visible devices, have "
                f"{len(jax.devices())} (CPU: set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={tp})"
            )
        ekw = dict(
            num_slots=num_slots, capacity=capacity,
            sampling=SamplingParams(temperature=0.0), seed=0,
            prefill_token_budget=budget, paged=True,
            page_size=page_size or (64 if on_tpu else 16),
        )

        def run_tp(m, p):
            eng = InferenceEngine(m, p, **ekw)
            eng.generate(prompts[:num_slots], max_new_tokens=3)
            eng.reset_stats()
            t0 = time.perf_counter()
            results = eng.generate(prompts, max_new_tokens=max_new)
            dt = time.perf_counter() - t0
            gen = sum(len(r.tokens) for r in results)
            return eng, [r.tokens for r in results], gen / dt, dt

        eng1, toks1, rate1, _ = run_tp(model, params)
        assert eng1.mixed_trace_count == 1
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            tp, 1, devices=jax.devices()[:tp]
        )
        model_tp = GPTModel(
            dataclasses.replace(cfg, tensor_parallel_size=tp)
        )
        params_tp = shard_tp1_params(model_tp, params, mesh)
        eng_t, toks_t, rate_t, dt_t = run_tp(model_tp, params_tp)
        assert eng_t.mixed_trace_count == 1, (
            f"tp={tp} mixed step traced {eng_t.mixed_trace_count}x"
        )
        assert toks1 == toks_t, (
            f"tp={tp} serve changed greedy tokens"
        )
        kv1, kvt = eng1.per_chip_kv_bytes(), eng_t.per_chip_kv_bytes()
        assert kvt * tp == kv1, (
            f"per-chip KV bytes {kvt} x{tp} != tp=1 {kv1}"
        )
        s1, s_t = eng1.stats(), eng_t.stats()
        chip_rate = rate_t / tp
        print(
            f"serve[tp{tp}]: {rate_t:.1f} gen tok/s over {dt_t:.2f}s "
            f"= {chip_rate:.1f}/chip vs tp1 {rate1:.1f}/chip "
            f"({chip_rate / rate1:.2f}x); tokens identical; per-chip "
            f"KV {kvt / 2**20:.1f} MiB vs {kv1 / 2**20:.1f} MiB "
            f"(1/{tp}); ttft p95 {s_t['ttft_ms_p95']:.0f} vs "
            f"{s1['ttft_ms_p95']:.0f} ms",
            file=sys.stderr,
        )
        _report(
            f"gpt_serve_tokens_per_sec_per_chip_tp{tp}", chip_rate,
            "tokens/s", chip_rate / rate1,
            f"tp={tp} paged serve at equal chip count vs tp=1 "
            f"{rate1:.1f} tok/s/chip (ratio = vs_baseline); greedy "
            f"tokens identical, mixed step traced once, per-chip KV "
            f"bytes exactly 1/{tp}",
        )
        _report(
            f"gpt_serve_ttft_ms_tp{tp}", s_t["ttft_ms_p95"], "ms",
            s1["ttft_ms_p95"] / max(s_t["ttft_ms_p95"], 1e-9),
            f"enqueue->first-token p95 at tp={tp} vs tp=1 "
            f"{s1['ttft_ms_p95']:.0f} ms (ratio = vs_baseline)",
        )
        parallel_state.destroy_model_parallel()
        return

    if disagg:
        # ---- equal-chip-count disaggregation A/B: a prefill/decode
        # class fleet vs an identical-replica fleet, same chips, same
        # workload. Placement and page-shipping handoffs must be
        # invisible in tokens; the per-class TTFT split is the point.
        from rocm_apex_tpu.inference import ReplicaRouter

        n_rep = replicas if replicas >= 2 else 2
        classes = (
            ["prefill"] * (n_rep // 2)
            + ["decode"] * (n_rep - n_rep // 2)
        )
        # disaggregation amortizes one page-shipping handoff per
        # request over the DECODE phase: measure the decode-heavy
        # regime it exists for (the mixed workload's 6-token CPU tail
        # would be all handoff, no decode)
        dis_new = max_new if on_tpu else max_new * 8
        ekw = dict(
            num_slots=num_slots, capacity=capacity,
            max_prompt_len=max(lens),
            sampling=SamplingParams(temperature=0.0), seed=0,
            prefill_token_budget=budget, paged=True,
            page_size=page_size or (64 if on_tpu else 16),
        )

        def run_fleet(fleet_classes):
            router = ReplicaRouter(
                model, params, replicas=n_rep,
                engine_kwargs=dict(ekw),
                replica_classes=fleet_classes,
            )
            for i in range(router.num_replicas):
                router.replica(i).generate(
                    prompts[:num_slots], max_new_tokens=3
                )
                router.replica(i).reset_stats()
            t0 = time.perf_counter()
            results = router.generate(prompts, max_new_tokens=dis_new)
            dt = time.perf_counter() - t0
            gen = sum(len(r.tokens) for r in results)
            return router, results, gen / dt, dt

        # throwaway disagg pass: the page-ship import scatters compile
        # lazily on first handoff (one program per shipped-page count)
        # — warm jax's global jit cache so the timed passes measure
        # the serving fabric, not XLA
        run_fleet(classes)
        router_u, res_u, rate_u, _ = run_fleet(None)
        router_d, res_d, rate_d, dt_d = run_fleet(classes)
        assert [r.tokens for r in res_d] == [r.tokens for r in res_u], (
            "disagg fleet tokens diverged from the uniform fleet"
        )
        s_d = router_d.stats()
        assert s_d["handoffs"] >= 1, s_d
        assert s_d["page_migrations"] >= 1, s_d
        ships = 0
        for i in range(n_rep):
            rep = router_d.replica(i)
            ships += int(rep.stats().get("page_ships", 0))
            assert rep.num_active == 0 and rep.pages_used == 0, (
                f"disagg replica {i} leaked slots/pages"
            )
            rep._allocator.assert_consistent()
        assert ships >= 1, "no handoff actually shipped pages"
        # per-class TTFT p95 from the per-replica completion records,
        # attributed (like the router_ttft_ms histogram) to the class
        # of the replica that FINISHED the request
        ttft_all = [
            c["ttft_ms"]
            for i in range(n_rep)
            for c in router_u.replica(i).completions
            if c["ttft_ms"] > 0
        ]
        p95_u = float(np.percentile(ttft_all, 95)) if ttft_all else 0.0
        by_class = {}
        for i, c in enumerate(classes):
            by_class.setdefault(c, []).extend(
                rec["ttft_ms"]
                for rec in router_d.replica(i).completions
                if rec["ttft_ms"] > 0
            )
        chip_u, chip_d = rate_u / n_rep, rate_d / n_rep
        class_p95 = {
            c: float(np.percentile(v, 95))
            for c, v in by_class.items() if v
        }
        per_class = ", ".join(
            f"{c} p95={v:.0f}ms" for c, v in sorted(class_p95.items())
        )
        print(
            f"serve[disagg x{n_rep}]: {rate_d:.1f} gen tok/s "
            f"({chip_d:.1f}/chip) over {dt_d:.2f}s vs uniform "
            f"{rate_u:.1f} ({rate_d / rate_u:.2f}x); tokens identical; "
            f"{int(s_d['handoffs'])} handoffs, {ships} page ships; "
            f"ttft {per_class} vs uniform p95={p95_u:.0f}ms",
            file=sys.stderr,
        )
        _report(
            "gpt_serve_tokens_per_sec_per_chip_disagg", chip_d,
            "tokens/s", rate_d / rate_u,
            f"prefill/decode class fleet ({'+'.join(classes)}) vs "
            f"uniform x{n_rep} at equal chip count "
            f"(ratio = vs_baseline); tokens identical, "
            f"{int(s_d['handoffs'])} handoffs shipped {ships} page "
            f"payloads, both fleets leak-free",
        )
        for c, v in sorted(class_p95.items()):
            _report(
                f"gpt_serve_ttft_ms_{c}", v, "ms",
                p95_u / max(v, 1e-9),
                f"ttft p95 of requests FINISHED by {c}-class replicas "
                f"vs uniform-fleet p95 {p95_u:.0f} ms "
                f"(ratio = vs_baseline)",
            )
        if chaos >= 0:
            # ---- fleet-causal observability pass: the same disagg
            # fleet under a seeded mid-decode replica kill, with a
            # tracer on the router AND every replica, the retrace
            # sentinel armed after warmup, and the sensor ring
            # sampling the router registry every tick. Three
            # acceptance properties: (1) ONE merged Perfetto trace in
            # which every request — handed off, migrated, or failed
            # over — is a single trace_id lifeline with exactly one
            # finish; (2) the /timeseries-style windowed rate and
            # quantile queries agree with the cumulative counters and
            # see the seeded load doubling before the cumulative
            # average moves; (3) zero post-warmup XLA compiles.
            import os
            import tempfile

            from rocm_apex_tpu.inference import Fault, FaultPlan
            from rocm_apex_tpu.monitor.timeseries import TimeSeriesStore
            from rocm_apex_tpu.monitor.trace import Tracer, trace_lifelines

            rng_c = np.random.RandomState(chaos)
            victim = int(rng_c.randint(0, n_rep))
            kill_tick = int(rng_c.randint(4, 9))

            def run_observed(traced):
                # one tick-deterministic driver for both passes: the
                # throwaway pass (traced=False) replays the exact
                # schedule first so every kill-path page-ship gather
                # shape is compiled BEFORE the sentinel arms — the
                # traced pass then proves the serving fabric itself
                # never retraces
                plan = FaultPlan([
                    Fault(site="replica_kill", tick=kill_tick,
                          payload={"replica": victim}),
                ], seed=chaos)
                router = ReplicaRouter(
                    model, params, replicas=n_rep,
                    engine_kwargs=dict(ekw),
                    replica_classes=classes, faults=plan,
                    tracer=Tracer() if traced else None,
                    retrace_policy="count" if traced else None,
                )
                for i in range(router.num_replicas):
                    router.replica(i).generate(
                        prompts[:num_slots], max_new_tokens=3
                    )
                    router.replica(i).reset_stats()
                    if traced:
                        # fresh per-replica tracers AFTER warmup:
                        # merge_traces gives each its own process id
                        router.replica(i).tracer = Tracer()
                ts = None
                if traced:
                    ts = TimeSeriesStore(
                        router.registry, interval=1e-4, capacity=8192,
                    )
                    router.timeseries = ts  # step() ticks it
                    router.arm_retrace_sentinel()
                done = {}

                def tick():
                    for r in router.step():
                        done[r.request_id] = r

                # wave 1: paced arrival, one prompt per two ticks
                # (the kill fires mid-wave); drain to empty
                for p in prompts:
                    router.add_request(p, max_new_tokens=dis_new)
                    tick()
                    tick()
                guard = 0
                while router.has_work():
                    tick()
                    guard += 1
                    assert guard < 20000, "observability pass wedged"
                t2 = time.perf_counter()
                # wave 2: the seeded load doubling — twice the
                # request count offered in one burst
                for p in prompts + prompts:
                    router.add_request(p, max_new_tokens=dis_new)
                while router.has_work():
                    tick()
                    guard += 1
                    assert guard < 20000, "observability pass wedged"
                return router, ts, done, t2, plan

            run_observed(traced=False)
            router_t, ts, done, t2, plan = run_observed(traced=True)
            n_req = 3 * len(prompts)
            s_t = router_t.stats()
            assert plan.fires.get("replica_kill", 0) == 1, (
                f"replica_kill never fired: {dict(plan.fires)}"
            )
            assert len(done) == s_t["submitted"] == n_req, (
                len(done), s_t,
            )
            assert s_t["replica_kills"] >= 1 and s_t["migrations"] >= 1
            assert s_t["handoffs"] >= 1, s_t
            # (1) the merged fleet trace: one lifeline per request,
            # exactly one finish each, and the handed-off / failed-over
            # ones span more than one replica process
            trace_path = os.path.join(
                tempfile.gettempdir(),
                f"rocm_apex_disagg_fleet_trace_{os.getpid()}.json",
            )
            n_events = router_t.export_merged_trace(trace_path)
            lines = trace_lifelines(router_t.merged_trace())
            assert len(lines) == n_req, (len(lines), n_req)
            bad = {
                t: d for t, d in lines.items() if d["finishes"] != 1
            }
            assert not bad, f"lifelines without exactly one finish: {bad}"
            multi = [
                t for t, d in lines.items()
                if len([p for p in d["pids"] if p > 1]) > 1
            ]
            assert len(multi) >= len(prompts), (
                f"{int(s_t['handoffs'])} handoffs + "
                f"{int(s_t['migrations'])} migrations but only "
                f"{len(multi)} of {n_req} lifelines span 2+ replicas"
            )
            # (2) sensor plane vs cumulative counters: the full-ring
            # delta reproduces the cumulative completion count, and
            # the burst-window rate/quantile move while the
            # cumulative average still blends the paced wave
            t_end = time.perf_counter()
            assert int(round(ts.delta("router_ttft_ms"))) == n_req, (
                ts.delta("router_ttft_ms"), n_req,
            )
            w_burst = t_end - t2
            rate_burst = ts.rate("router_ttft_ms", window=w_burst)
            rate_full = ts.rate("router_ttft_ms")
            assert rate_burst > rate_full, (
                f"burst-window finish rate {rate_burst:.2f}/s did not "
                f"exceed the cumulative average {rate_full:.2f}/s"
            )
            q_burst = ts.quantile_over(
                "router_ttft_ms", 0.95, window=w_burst
            )
            q_full = ts.quantile_over("router_ttft_ms", 0.95)
            assert q_burst >= q_full, (q_burst, q_full)
            # (3) the armed sentinel saw no compile anywhere in the
            # process across kill, failover, migration, and handoff
            tripped = int(router_t.retrace_sentinel.tripped)
            assert tripped == 0, (
                f"post-warmup compiles: "
                f"{router_t.retrace_sentinel.status()}"
            )
            print(
                f"serve[disagg x{n_rep} chaos seed={chaos}]: killed "
                f"replica {victim} at tick {kill_tick}; {n_req} "
                f"requests -> {len(lines)} lifelines, every finish "
                f"exactly once, {len(multi)} span 2+ replicas "
                f"({int(s_t['handoffs'])} handoffs, "
                f"{int(s_t['migrations'])} migrations); merged trace "
                f"{n_events} events -> {trace_path}; sensor ring "
                f"{len(ts)} samples: burst rate {rate_burst:.2f}/s vs "
                f"cumulative {rate_full:.2f}/s, ttft p95 "
                f"{q_burst:.0f}ms vs {q_full:.0f}ms; retrace sentinel "
                f"{tripped} post-warmup compiles",
                file=sys.stderr,
            )
            _report(
                "gpt_serve_retrace_sentinel", float(tripped),
                "compiles", 1.0,
                f"post-warmup XLA compiles observed by the armed "
                f"retrace sentinel across the chaos-composed disagg "
                f"pass (seed={chaos}: replica kill, failover "
                f"migration, prefill->decode handoffs, load "
                f"doubling); every request one trace_id lifeline "
                f"with exactly one finish in the merged fleet trace",
            )
        return

    if replicas >= 2:
        from rocm_apex_tpu.inference import Fault, FaultPlan, ReplicaRouter

        ekw = dict(
            num_slots=num_slots, capacity=capacity,
            max_prompt_len=max(lens),
            sampling=SamplingParams(temperature=0.0), seed=0,
            prefill_token_budget=budget,
        )
        if paged:
            ekw.update(
                paged=True,
                page_size=page_size or (64 if on_tpu else 16),
                kv_dtype=jnp.int8 if kv_dtype == "int8" else None,
            )

        # the undisturbed single-replica run is BOTH the rate baseline
        # and the token-parity anchor: placement and recovery must
        # never change greedy outputs
        eng_ref, res_ref, rate_ref, _ = run(True)
        ref_tokens = [r.tokens for r in res_ref]
        assert eng_ref.mixed_trace_count == 1

        def run_fleet(plan):
            router = ReplicaRouter(
                model, params, replicas=replicas,
                engine_kwargs=dict(ekw), faults=plan,
            )
            # per-replica compile warmup (the router's tick counter
            # stays 0, so seeded fault ticks land in the timed window)
            for i in range(router.num_replicas):
                router.replica(i).generate(
                    prompts[:num_slots], max_new_tokens=3
                )
                router.replica(i).reset_stats()
            t0 = time.perf_counter()
            results = router.generate(prompts, max_new_tokens=max_new)
            dt = time.perf_counter() - t0
            gen = sum(len(r.tokens) for r in results)
            return router, results, gen / dt, dt

        def check_fleet(router, results, label):
            # the ISSUE-15 survival identity, asserted on every fleet
            # pass (clean and chaotic alike)
            assert [r.tokens for r in results] == ref_tokens, (
                f"{label}: fleet tokens diverged from the "
                f"single-replica reference"
            )
            rids = [r.request_id for r in results]
            assert len(results) == n_requests == len(set(rids)), (
                f"{label}: {n_requests} submitted, {len(results)} "
                f"delivered ({len(set(rids))} unique)"
            )
            s = router.stats()
            assert s["completed"] == s["submitted"] == n_requests, s
            for i in range(router.num_replicas):
                rep = router.replica(i)
                assert rep.mixed_trace_count == 1, (
                    f"{label}: replica {i} traced the mixed step "
                    f"{rep.mixed_trace_count}x"
                )
                assert rep.num_active == 0 and rep.pages_used == 0, (
                    f"{label}: replica {i} leaked slots/pages"
                )
                if paged:
                    rep._allocator.assert_consistent()
            # the merged scrape reproduces the combined per-replica
            # completion streams (bucket adds are exact)
            merged = router.merged_registry().get("serve_ttft_ms")
            per_rep = sum(
                router.replica(i).registry.get("serve_ttft_ms").count()
                for i in range(router.num_replicas)
            )
            assert merged.count() == per_rep == n_requests, (
                f"{label}: merged ttft count {merged.count()} != "
                f"sum of replicas {per_rep} != {n_requests}"
            )
            return s

        router_f, res_f, rate_f, dt_f = run_fleet(None)
        s_f = check_fleet(router_f, res_f, f"fleet x{replicas}")
        survival = "clean pass"
        if chaos >= 0:
            # seed-derived replica fault plan: one mid-decode kill plus
            # one slow-replica injection — replays bit-for-bit from the
            # same command line
            rng_c = np.random.RandomState(chaos)
            victim = int(rng_c.randint(0, replicas))
            plan = FaultPlan([
                Fault(site="replica_kill",
                      tick=int(rng_c.randint(3, 8)),
                      payload={"replica": victim}),
                Fault(site="replica_slow",
                      tick=int(rng_c.randint(8, 12)),
                      payload={"replica": (victim + 1) % replicas,
                               "seconds": 0.001}),
            ], seed=chaos)
            router_c, res_c, _, _ = run_fleet(plan)
            s_c = check_fleet(router_c, res_c, f"chaos seed={chaos}")
            assert plan.fires.get("replica_kill", 0) == 1, (
                f"replica_kill never fired: {dict(plan.fires)}"
            )
            assert s_c["replica_kills"] >= 1, s_c
            assert s_c["migrations"] >= 1, (
                "kill mid-decode migrated no in-flight work"
            )
            survival = (
                f"chaos seed={chaos}: killed replica {victim}, "
                f"{int(s_c['migrations'])} migrations, "
                f"{int(s_c['replica_rejoins'])} rejoins — recovered "
                f"tokens bitwise-identical, no request lost or "
                f"double-delivered, killed replica's slots/pages clean"
            )
        if metrics_port >= 0:
            # fleet exporter: zero-arg merged-registry provider + the
            # fleet /healthz (503 only when NO replica is healthy)
            import http.client
            import json as _json

            srv = monitor.start_exporter(
                router=router_f, port=metrics_port
            )
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=10
                )
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                body = resp.read()
                assert resp.status == 200, resp.status
                assert b"serve_ttft_ms_count" in body
                assert b"router_events_total" in body
                conn.request("GET", "/healthz")
                hz = conn.getresponse()
                healthy = _json.loads(hz.read()).get("healthy")
                assert hz.status == 200 and healthy, (hz.status, healthy)
                conn.close()
                print(
                    f"serve fleet metrics: {srv.url} — /metrics "
                    f"{len(body)} bytes (merged per scrape), /healthz "
                    f"200 with {int(s_f['healthy_replicas'])} healthy",
                    file=sys.stderr,
                )
            finally:
                srv.close()
        print(
            f"serve[fleet x{replicas}{'/paged' if paged else ''}]: "
            f"{rate_f:.1f} gen tok/s over {dt_f:.2f}s vs 1-replica "
            f"{rate_ref:.1f} ({rate_f / rate_ref:.2f}x); tokens "
            f"identical to the single-replica reference; {survival}",
            file=sys.stderr,
        )
        _report(
            "gpt_serve_fleet_tokens_per_sec", rate_f, "tokens/s",
            rate_f / rate_ref,
            f"{replicas}-replica ReplicaRouter vs single replica "
            f"{rate_ref:.1f} tok/s (ratio = vs_baseline); every "
            f"request accounted exactly once, fleet tokens "
            f"bitwise-identical to the 1-replica reference, merged "
            f"/metrics ttft count == sum of replicas; {survival}",
        )
        return

    if chaos >= 0:
        from rocm_apex_tpu.inference import FINISH_REASONS, Fault, FaultPlan

        kv = jnp.int8 if kv_dtype == "int8" else None
        ps = page_size or (64 if on_tpu else 16)
        ttft_threshold = 0.0
        backoff = 0.0
        if slo:
            # calibration: the same workload fault-free fixes the
            # alert threshold (2x its ttft p95) and must stay quiet
            # against it — the no-false-positive half of the assert
            eng_cal, _, _, _ = run(True)
            p95_cal = eng_cal.stats()["ttft_ms_p95"]
            ttft_threshold = max(2.0 * p95_cal, 1.0)
            mon_quiet = slo_replay_ttft(
                eng_cal.completions, ttft_threshold
            )
            assert not mon_quiet.events, (
                f"fault-free calibration run tripped the TTFT burn "
                f"alert: {mon_quiet.events}"
            )
            backoff = min(1.0, max(0.05, p95_cal / 1000.0))
        # the schedule derives from SEED alone, so a red run replays
        # bit-for-bit with the same command line
        rng_c = np.random.RandomState(chaos)
        faults = [
            Fault(site="device_step", tick=int(rng_c.randint(1, 5))),
            Fault(site="logits", tick=int(rng_c.randint(5, 10)),
                  payload={"slot": int(rng_c.randint(0, num_slots))}),
            Fault(site="host_fetch", p=0.05, times=2),
            # consulted on the paged engine only; 0 fires on contiguous
            Fault(site="page_alloc", nth=int(rng_c.randint(2, 7))),
        ]
        if slo:
            # latency burst: six consecutive mid-run ticks each lose
            # one device-step attempt (distinct ticks, times=1 each —
            # retries cannot exhaust on them) and step_retry_backoff
            # stalls each retry ~one fault-free p95, so the requests
            # queued behind the burst blow through the 2x-p95 alert
            # threshold while the early wave stays under it
            faults.extend(
                Fault(site="device_step", tick=t)
                for t in range(10, 16)
            )
        plan = FaultPlan(faults, seed=chaos)
        eng = InferenceEngine(
            model, params, num_slots=num_slots, capacity=capacity,
            max_prompt_len=max(lens),
            sampling=SamplingParams(temperature=0.0), seed=0,
            prefill_token_budget=budget, faults=plan,
            # p=0.05 times=2 can never out-fire 3 attempts — the plan
            # is chaotic, not unrecoverable (under --slo the burst
            # adds ONE deterministic fire per tick, so the margin
            # needs one more retry)
            max_step_retries=3 if slo else 2,
            step_retry_backoff=backoff,
            # bounded admission: the last 2 submissions shed
            max_queue=n_requests - 2,
            paged=paged, page_size=ps if paged else 16,
            kv_dtype=kv if paged else None,
        )
        baseline = eng._allocator.snapshot() if paged else None
        for p in prompts:
            eng.add_request(p, max_new_tokens=max_new)
        done = {}
        for _ in range(2):
            for r in eng.step():
                done[r.request_id] = r
        victim = next(
            st.req.request_id for st in eng._slots if st is not None
        )
        done[victim] = eng.cancel(victim)
        done.update(
            {r.request_id: r for r in eng.drain()}
        )
        s = eng.stats()
        shed = int(s["shed"])
        quar = int(s["quarantined"])
        canc = int(s["cancelled"])
        dead = int(s["deadline_exceeded"])
        reasons = {}
        for c in eng.completions:
            reasons[c["finish_reason"]] = (
                reasons.get(c["finish_reason"], 0) + 1
            )
        finished_ok = sum(
            n for why, n in reasons.items()
            if why in ("length", "stop", "capacity")
        )
        # the accounting identity: one record per submission, every
        # record a known reason, the teardown counters summing exactly
        assert len(eng.completions) == n_requests, (
            f"{n_requests} submitted, {len(eng.completions)} accounted"
        )
        assert set(reasons) <= set(FINISH_REASONS), reasons
        assert (
            finished_ok + shed + quar + canc + dead == n_requests
        ), (
            f"completion accounting leaked: {finished_ok} completed + "
            f"{shed} shed + {quar} quarantined + {canc} cancelled + "
            f"{dead} expired != {n_requests} submitted ({reasons})"
        )
        assert quar == reasons.get("error", 0)
        assert eng.mixed_trace_count == 1, "chaos retraced the mixed step"
        assert sum(plan.fires.values()) >= 2, (
            f"chaos plan barely fired: {dict(plan.fires)}"
        )
        if paged:
            eng._allocator.assert_consistent()
            assert eng._allocator.snapshot() == baseline, (
                "pages leaked across the chaos run"
            )
        print(
            f"serve[chaos seed={chaos}{'/paged' if paged else ''}]: "
            f"{finished_ok} completed, {shed} shed, {quar} "
            f"quarantined, {canc} cancelled, {dead} expired of "
            f"{n_requests}; retries={int(s['step_retries'])} "
            f"fires={dict(plan.fires)} — accounting identity holds",
            file=sys.stderr,
        )
        _report(
            "gpt_serve_chaos_survival", float(finished_ok), "requests",
            finished_ok / n_requests,
            f"seeded chaos (seed={chaos}): completed + shed + "
            f"quarantined + cancelled + expired == submitted "
            f"({n_requests}); mixed step traced once; "
            f"{'no page leak; ' if paged else ''}"
            f"fault fires {dict(plan.fires)}",
        )
        if slo:
            mon_chaos = slo_replay_ttft(eng.completions, ttft_threshold)
            n_alerts = len(mon_chaos.events)
            assert n_alerts > 0, (
                f"chaos latency burst did not trip the TTFT burn-rate "
                f"alert (threshold {ttft_threshold:.0f} ms, fires "
                f"{dict(plan.fires)})"
            )
            _report(
                "gpt_serve_slo_alerts", float(n_alerts), "alerts", 1.0,
                f"ttft burn-rate: chaos fired {n_alerts} alert(s) at "
                f"threshold {ttft_threshold:.0f} ms (2x fault-free "
                f"p95); fault-free calibration pass stayed quiet",
            )
        if metrics_port >= 0:
            scrape_metrics(eng)
        return

    if paged or shared_prefix:
        kv = jnp.int8 if kv_dtype == "int8" else None
        ps = page_size or (64 if on_tpu else 16)
        suffix = "_int8" if kv is not None else ""

        def build_paged(sharing):
            return InferenceEngine(
                model, params, num_slots=num_slots, capacity=capacity,
                sampling=SamplingParams(temperature=0.0), seed=0,
                prefill_token_budget=budget, paged=True, page_size=ps,
                kv_dtype=kv, prefix_sharing=sharing,
            )

        def run_steps(eng):
            # warmup compiles on the same engine; under prefix sharing
            # it ALSO registers the shared prefix, so the timed window
            # measures steady-state serving (warm store). The second
            # tiny pass replays a TRUNCATED first prompt that ends
            # INSIDE a stored page (the prefix is not page-aligned):
            # partial borrow -> the copy-on-write fork program
            # compiles here, not in the timed window
            eng.generate(prompts[:num_slots], max_new_tokens=3)
            if eng.prefix_sharing and shared_prefix:
                eng.generate(
                    [prompts[0][:prefix_len + 2]], max_new_tokens=3
                )
            eng.reset_stats()
            ids = [
                eng.add_request(p, max_new_tokens=max_new)
                for p in prompts
            ]
            done = {}
            peak_pages = 0
            t0 = time.perf_counter()
            while eng.has_work():
                for r in eng.step():
                    done[r.request_id] = r
                if eng.paged:
                    peak_pages = max(
                        peak_pages, int(eng.stats()["pages_used"])
                    )
            dt = time.perf_counter() - t0
            results = [done[i] for i in ids]
            gen = sum(len(r.tokens) for r in results)
            return eng, results, gen / dt, dt, eng.stats(), peak_pages

        if shared_prefix:
            _, res_b, tok_b, dt_b, s_b, _ = run_steps(build_paged(False))
            _, res_s, tok_s, dt_s, s_s, _ = run_steps(build_paged(True))
            # sharing maps the SAME materialized pages a private
            # prefill would have produced — tokens must not move
            for rb, rs in zip(res_b, res_s):
                assert rb.tokens == rs.tokens, (
                    f"prefix sharing changed tokens on request "
                    f"{rs.request_id}"
                )
            assert s_s["prefix_hits"] > 0, "no prefix hits measured"
            for mode, tk, dt, s in (
                ("paged", tok_b, dt_b, s_b),
                ("paged+shared", tok_s, dt_s, s_s),
            ):
                print(
                    f"serve[{mode}{suffix}]: {tk:.1f} gen tok/s over "
                    f"{dt:.2f}s ttft p95={s['ttft_ms_p95']:.0f}ms "
                    f"prefix_hits={s['prefix_hits']:.0f} "
                    f"hit_tokens={s['prefix_hit_tokens']:.0f} "
                    f"cow_forks={s['cow_forks']:.0f}",
                    file=sys.stderr,
                )
            _report(
                f"gpt_serve_tokens_per_sec_per_chip_shared_prefix{suffix}",
                tok_s, "tokens/s", tok_s / tok_b,
                f"prefix sharing {tok_s:.1f} vs plain paged "
                f"{tok_b:.1f} tok/s; {s_s['prefix_hit_tokens']:.0f} "
                f"prompt tokens never re-prefilled; tokens identical",
            )
            _report(
                f"gpt_serve_ttft_ms_shared_prefix{suffix}",
                s_s["ttft_ms_p95"], "ms",
                s_b["ttft_ms_p95"] / max(s_s["ttft_ms_p95"], 1e-9),
                f"ttft p95: shared {s_s['ttft_ms_p95']:.0f} ms vs "
                f"plain paged {s_b['ttft_ms_p95']:.0f} ms "
                f"(ratio = vs_baseline)",
            )
            return

        # plain paged A/B against the contiguous chunked engine
        eng_c, res_c, tok_c, dt_c = run(True)
        s_c = eng_c.stats()
        eng_p, res_p, tok_p, dt_p, s_p, peak = run_steps(
            build_paged(False)
        )
        if kv is None:
            for rc, rp in zip(res_c, res_p):
                assert rc.tokens == rp.tokens, (
                    f"paged/contiguous token mismatch on request "
                    f"{rp.request_id}"
                )
            parity = "tokens identical"
        else:
            same = sum(
                rc.tokens == rp.tokens for rc, rp in zip(res_c, res_p)
            )
            parity = f"int8 greedy match {same}/{len(res_c)} requests"
        cont_bytes = eng_c.cache_bytes()
        pool_bytes = eng_p.cache_bytes()
        num_pages = eng_p.cache.num_pages
        live_bytes = int(pool_bytes * peak / max(num_pages, 1))
        mb = 1.0 / (1024 * 1024)
        print(
            f"serve[paged{suffix}]: {tok_p:.1f} gen tok/s over "
            f"{dt_p:.2f}s (page_size={ps}) vs contiguous {tok_c:.1f}; "
            f"cache bytes: contiguous {cont_bytes*mb:.2f} MiB, paged "
            f"pool {pool_bytes*mb:.2f} MiB, peak LIVE "
            f"{live_bytes*mb:.2f} MiB ({peak}/{num_pages} pages) — "
            f"{parity}",
            file=sys.stderr,
        )
        _report(
            f"gpt_serve_tokens_per_sec_per_chip_paged{suffix}",
            tok_p, "tokens/s", tok_p / tok_c,
            f"paged {tok_p:.1f} vs contiguous {tok_c:.1f} tok/s; "
            f"{parity}; peak live cache {live_bytes*mb:.2f} MiB vs "
            f"contiguous {cont_bytes*mb:.2f} MiB",
        )
        _report(
            f"gpt_serve_ttft_ms_paged{suffix}",
            s_p["ttft_ms_p95"], "ms",
            s_c["ttft_ms_p95"] / max(s_p["ttft_ms_p95"], 1e-9),
            f"ttft p95: paged {s_p['ttft_ms_p95']:.0f} ms vs "
            f"contiguous {s_c['ttft_ms_p95']:.0f} ms "
            f"(ratio = vs_baseline)",
        )
        return

    # --trace instruments the MEASURED mode (chunked, or whole under
    # --whole-prompt) — the A/B contrast numbers stay tracer-free
    tracer = monitor.Tracer() if trace else None
    traced_mode = "whole" if whole_prompt else "chunked"
    modes = ["whole"] if whole_prompt else ["whole", "chunked"]
    out = {}
    for mode in modes:
        eng, results, tok_s, dt = run(
            mode == "chunked",
            tracer if mode == traced_mode else None,
        )
        s = eng.stats()
        out[mode] = (tok_s, s, results)
        if trace and mode == traced_mode:
            n = tracer.export_chrome_trace(trace)
            req_path = trace + ".requests.jsonl"
            with open(req_path, "w") as f:
                w = monitor.JsonlWriter(stream=f)
                for rec in eng.completions:
                    w.emit(rec)
            print(
                f"serve trace: {n} events -> {trace}; "
                f"{len(eng.completions)} request records -> {req_path}",
                file=sys.stderr,
            )
        print(
            f"serve[{mode}]: {tok_s:.1f} gen tok/s over {dt:.2f}s "
            f"(prompt_tokens={total_prompt} budget="
            f"{budget if mode == 'chunked' else 'whole'}) "
            f"ttft p50/p95={s['ttft_ms_p50']:.0f}/"
            f"{s['ttft_ms_p95']:.0f}ms "
            f"queue_wait p95={s['queue_wait_ms_p95']:.0f}ms "
            f"mixed_traces={eng.mixed_trace_count} "
            f"prefill_traces={eng.prefill_trace_count}",
            file=sys.stderr,
        )
    if slo:
        # fault-free serving must not page anyone: replay the measured
        # run's TTFTs against a threshold budgeted off its own p95 —
        # the quiet half of the --chaos --slo acceptance pair
        s_m = out[traced_mode][1]
        thresh = max(2.0 * s_m["ttft_ms_p95"], 1.0)
        mon = slo_replay_ttft(eng.completions, thresh)
        assert not mon.events, (
            f"fault-free serve run tripped the TTFT burn alert: "
            f"{mon.events}"
        )
        _report(
            "gpt_serve_slo_alerts", 0.0, "alerts", 1.0,
            f"ttft burn-rate quiet on the fault-free {traced_mode} "
            f"run (threshold {thresh:.0f} ms = 2x its p95)",
        )
    if metrics_port >= 0:
        scrape_metrics(eng)
    if whole_prompt:
        tok_s, s, _ = out["whole"]
        _report("gpt_serve_tokens_per_sec_per_chip_whole", tok_s,
                "tokens/s", 1.0, "")
        _report("gpt_serve_ttft_ms_whole", s["ttft_ms_p95"], "ms", 1.0,
                "")
        return
    # greedy outputs must be token-identical across the A/B pair — a
    # throughput win that changes tokens is not a win
    for rc, rw in zip(out["chunked"][2], out["whole"][2]):
        assert rc.tokens == rw.tokens, (
            f"chunked/whole token mismatch on request {rc.request_id}"
        )
    tok_c, s_c, _ = out["chunked"]
    tok_w, s_w, _ = out["whole"]
    _report(
        "gpt_serve_tokens_per_sec_per_chip", tok_c, "tokens/s",
        tok_c / tok_w,
        f"chunked {tok_c:.1f} vs whole-prompt {tok_w:.1f} tok/s "
        f"(speedup = vs_baseline); tokens identical",
    )
    _report(
        "gpt_serve_ttft_ms", s_c["ttft_ms_p95"], "ms",
        s_w["ttft_ms_p95"] / max(s_c["ttft_ms_p95"], 1e-9),
        f"ttft p95: chunked {s_c['ttft_ms_p95']:.0f} ms vs whole "
        f"{s_w['ttft_ms_p95']:.0f} ms (ratio = vs_baseline)",
    )


def _lint_head_is_chunked(cfg, batch: int, seq: int) -> bool:
    """True when the fused LM head really tiles (b·s, vocab): with few
    rows the op's default chunk covers them all and the single tile IS
    logits-shaped by design, so the no-materialization probe would
    flag a non-violation."""
    from rocm_apex_tpu.ops.linear_xentropy import _chunk_rows

    rows = batch * seq
    return _chunk_rows(rows, cfg.vocab_size, cfg.lm_head_chunk_size) < rows


def _timed_scan(step, init, iters):
    """ms per iteration of `step` (carry -> carry) inside one dispatch.

    The carry must make each iteration depend on the last or XLA hoists
    the body out of the loop. Transport overhead (the axon tunnel's
    ~100 ms dispatch+fetch RTT, which swamps sub-ms kernels) is
    cancelled exactly by timing scans of length N and 2N and taking
    (T(2N) - T(N)) / N; each is timed 3x and the minima are differenced
    (min is the low-noise duration estimator).
    `block_until_ready` does not synchronize on the tunnel, so syncs
    are scalar fetches."""

    def sync(tree):
        leaf = jax.tree_util.tree_leaves(tree)[0]
        float(leaf.reshape(-1)[0].astype(jnp.float32))

    def make(n):
        @jax.jit
        def many(c):
            return jax.lax.scan(
                lambda c, _: (step(c), None), c, None, length=n
            )[0]

        return many

    many_n, many_2n = make(iters), make(2 * iters)
    c = many_n(init)
    sync(c)
    c2 = many_2n(init)
    sync(c2)

    def best(f):
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            sync(f(init))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    dt = best(many_2n) - best(many_n)
    if dt <= 0:
        # RTT jitter exceeded the device time at this scan length:
        # re-measure at 4x before giving up (never silently report
        # noise as an absurdly fast kernel)
        many_4n, many_8n = make(4 * iters), make(8 * iters)
        sync(many_4n(init))
        sync(many_8n(init))
        dt = (best(many_8n) - best(many_4n)) / 4.0
        if dt <= 0:
            raise RuntimeError(
                "timing noise exceeded device time even at 8x iters; "
                "raise `iters` for this bench"
            )
    return dt / iters * 1000.0


def bench_attn():
    """Long-context flash attention sweep (the BASELINE.md long-context
    rows; the reference's perf-test analogue is
    apex/contrib/examples/multihead_attn/perf_test_multihead_attn.py —
    its kernels cap at seqlen 512/2048, this sweep runs to 32k)."""
    from rocm_apex_tpu.ops.flash_attention import flash_attention

    on_tpu = jax.default_backend() == "tpu"
    bh, hd = 8, 128
    seqs = (8192, 16384, 32768) if on_tpu else (256,)
    rows = []
    for s in seqs:
        # enough iterations that RTT jitter (±~15 ms across dispatches)
        # stays well under the per-iter signal
        iters = max(10, 400_000 // s) if on_tpu else 2
        q, k, v = (
            jax.random.normal(jax.random.PRNGKey(i), (bh, s, hd), jnp.bfloat16)
            for i in range(3)
        )

        def step(carry, q=q, k=k, v=v):
            q2, acc = carry

            def loss(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, None, True).astype(jnp.float32)
                    ** 2
                )

            l, grads = jax.value_and_grad(loss, (0, 1, 2))(q2, k, v)
            g = sum(jnp.sum(t.astype(jnp.float32)) for t in grads)
            # feed the loss back into q at 1e-30 scale: numerically a
            # no-op in bf16, but it defeats loop-invariant hoisting
            return q2 + (l * 1e-30).astype(q2.dtype), acc + l + g

        ms = _timed_scan(step, (q, jnp.float32(0)), iters)
        # 7 block-matmuls (2 fwd + 5 merged bwd) x 2*hd MAC-FLOPs per
        # score position, over the causal half: 7 * 2*hd * bh * s^2/2
        flops = 7.0 * bh * s * s * hd
        tf = flops / (ms / 1000.0) / 1e12
        rows.append((s, ms, tf))
        print(f"attn s={s}: {ms:.1f} ms/iter  {tf:.1f} eff TFLOP/s",
              file=sys.stderr)
    s, ms, tf = rows[-1]
    _report(
        "flash_attention_fwd_bwd_ms_s32k" if on_tpu else "flash_attention_ms",
        ms, "ms",
        (tf * 1e12) / peak_flops_per_chip(),
        f"sweep: {', '.join(f's={s}: {m:.1f}ms' for s, m, _ in rows)}",
    )


def bench_fmha():
    """Packed-native vs padded-batch varlen attention at high
    raggedness (the BASELINE.md fmha row; reference design point:
    apex/contrib/fmha packed kernels). 64 sequences drawn from a
    long-tailed length mix padding to max_s=2048: the padded path pays
    b*max_s, the packed path pays O(total)."""
    import numpy as np

    from rocm_apex_tpu.contrib.fmha import fmha

    on_tpu = jax.default_backend() == "tpu"
    h, d = 8, 64
    if on_tpu:
        rng = np.random.RandomState(0)
        lens = rng.choice(
            [64, 128, 256, 512, 2048], size=64, p=[0.3, 0.3, 0.2, 0.15, 0.05]
        ).tolist()
        iters = 20
    else:
        lens = [32, 64, 8]
        iters = 2
    max_s = max(lens)
    cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
    total = int(cu[-1])
    qkv = 0.5 * jax.random.normal(
        jax.random.PRNGKey(0), (total, 3, h, d), jnp.bfloat16
    )
    print(
        f"fmha raggedness: b={len(lens)} total={total} "
        f"b*max_s={len(lens) * max_s}",
        file=sys.stderr,
    )

    results = {}
    for name, packed in (("packed", True), ("padded", False)):
        def step(carry, packed=packed):
            x, acc = carry

            def loss(x):
                return jnp.sum(
                    fmha(
                        x, cu, max_s, causal=True, packed=packed
                    ).astype(jnp.float32) ** 2
                )

            l, g = jax.value_and_grad(loss)(x)
            tot = l + jnp.sum(g.astype(jnp.float32))
            return x + (tot * 1e-30).astype(x.dtype), acc + tot

        results[name] = _timed_scan(step, (qkv, jnp.float32(0)), iters)
        print(f"fmha {name}: {results[name]:.2f} ms fwd+bwd", file=sys.stderr)
    _report(
        "fmha_packed_native_fwd_bwd_ms", results["packed"], "ms",
        results["padded"] / results["packed"],
        f"packed {results['packed']:.2f} ms vs padded "
        f"{results['padded']:.2f} ms (speedup = vs_baseline)",
    )


def bench_optim():
    """Optimizer micro-bench on the 134M-param GPT tree (the BASELINE.md
    optimizer row): parity `fused_adam` (XLA-tree-fused) vs
    `MixedPrecisionAdam.step_and_probe`."""
    from rocm_apex_tpu.models.gpt import GPTConfig, GPTModel
    from rocm_apex_tpu.optimizers import fused_adam
    from rocm_apex_tpu.optimizers.mixed import MixedPrecisionAdam

    on_tpu = jax.default_backend() == "tpu"
    iters = 50 if on_tpu else 2
    cfg = GPTConfig(
        vocab_size=32768 if on_tpu else 512,
        hidden_size=1024 if on_tpu else 64,
        num_layers=8 if on_tpu else 2,
        num_attention_heads=8 if on_tpu else 4,
        max_position_embeddings=1024 if on_tpu else 64,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_parallel_size=1,
    )
    tokens = jnp.zeros((1, 64), jnp.int32)
    params = GPTModel(cfg).init(jax.random.PRNGKey(0), tokens)
    # runtime-derived grads (a constant tree would let XLA fold the
    # moment updates below their real bandwidth cost)
    grads = jax.tree_util.tree_map(
        lambda p: (p * 1e-3 + 1e-5).astype(jnp.bfloat16), params
    )
    n = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))

    opt = fused_adam(1e-4, weight_decay=0.01)
    o_state = opt.init(params)

    import optax

    def step_parity(carry):
        p, s, g = carry
        updates, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s2, g

    ms_parity = _timed_scan(step_parity, (params, o_state, grads), iters)

    mp = MixedPrecisionAdam(1e-4, weight_decay=0.01)
    m_state = mp.init(params)

    def step_mixed(carry):
        state, g = carry
        state2, _ = mp.step_and_probe(state, g, grad_scale=1.0)
        return state2, g

    ms_mixed = _timed_scan(step_mixed, (m_state, grads), iters)
    print(
        f"optim ({n/1e6:.0f}M tree): fused_adam {ms_parity:.2f} ms, "
        f"MixedPrecisionAdam.step_and_probe {ms_mixed:.2f} ms",
        file=sys.stderr,
    )
    # fp32 p/m/v read+write + bf16 grads read ≈ 26 bytes/param
    floor_ms = 26.0 * n / 819e9 * 1000 if on_tpu else None
    _report(
        "mixed_precision_adam_step_ms", ms_mixed, "ms",
        (floor_ms / ms_mixed) if floor_ms else 0.0,
        f"vs bandwidth floor {floor_ms:.2f} ms" if floor_ms else "",
    )


def bench_ln():
    """Fused LayerNorm micro-bench (the BASELINE.md LN row; reference
    perf scaffolding: apex/contrib/test fast LN tests). Measures the
    Pallas LN fwd+bwd on GPT-bench-shaped rows vs the jnp composition."""
    from rocm_apex_tpu.normalization.fused_layer_norm import (
        fused_layer_norm_affine,
    )

    on_tpu = jax.default_backend() == "tpu"
    rows, hidden = (16384, 1024) if on_tpu else (64, 32)
    iters = 100 if on_tpu else 2
    x = jax.random.normal(jax.random.PRNGKey(0), (rows, hidden), jnp.bfloat16)
    g = jnp.ones((hidden,), jnp.float32)
    b = jnp.zeros((hidden,), jnp.float32)

    def jnp_ln(x, g, b):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        return ((xf - mu) * jax.lax.rsqrt(var + 1e-5) * g + b).astype(x.dtype)

    results = {}

    def pallas_ln(x, g, b):
        return fused_layer_norm_affine(x, g, b, (hidden,), 1e-5)

    for name, fn in (("pallas", pallas_ln), ("xla", jnp_ln)):
        def step(carry, fn=fn):
            x2, acc = carry
            l, (gx, gg, gb) = jax.value_and_grad(
                lambda x, g, b: jnp.sum(fn(x, g, b).astype(jnp.float32) ** 2),
                (0, 1, 2),
            )(x2, g, b)
            tot = l + sum(
                jnp.sum(t.astype(jnp.float32)) for t in (gx, gg, gb)
            )
            return x2 + (tot * 1e-30).astype(x2.dtype), acc + tot

        results[name] = _timed_scan(step, (x, jnp.float32(0)), iters)
        print(f"ln {name}: {results[name]:.3f} ms fwd+bwd", file=sys.stderr)
    _report(
        "fused_layer_norm_fwd_bwd_ms", results["pallas"], "ms",
        results["xla"] / results["pallas"],
        f"pallas {results['pallas']:.3f} ms vs xla {results['xla']:.3f} ms",
    )


def main(dropout: float = 0.1, seq: int = 0, batch: int = 0,
         remat: bool = False, loss: str = "fused",
         seq_parallel: bool = False, collective_matmul: bool = False,
         audit: bool = False, lint: bool = False, dist_opt: bool = False,
         packed_update: bool = False, comm_dtype: str = "fp32"):
    if loss not in ("fused", "naive"):
        raise SystemExit(f"--loss must be 'fused' or 'naive', got {loss!r}")
    if collective_matmul and not seq_parallel:
        raise SystemExit("--collective-matmul requires --seq-parallel")
    if comm_dtype not in ("fp32", "int8"):
        raise SystemExit(
            f"--comm-dtype must be 'fp32' or 'int8', got {comm_dtype!r}"
        )
    if comm_dtype != "fp32" and not (dist_opt or collective_matmul):
        raise SystemExit(
            "--comm-dtype=int8 quantizes ring collectives; it needs "
            "--dist-opt (ZeRO grad/param rings) or --collective-matmul "
            "(TP-boundary rings) to have a ring to quantize"
        )
    if dist_opt and seq_parallel:
        raise SystemExit(
            "--dist-opt does not compose with --seq-parallel"
        )
    if dist_opt and loss != "fused":
        raise SystemExit("--dist-opt measures the fused-loss path")
    if packed_update and (dist_opt or seq_parallel):
        raise SystemExit(
            "--packed-update A/Bs the replicated optimizer step; the "
            "ZeRO path (--dist-opt) is always packed and the tp series "
            "keys on the model sharding"
        )
    if lint and dist_opt:
        raise SystemExit(
            "--lint checks the replicated train step; the ZeRO path's "
            "contracts live in tools/graphlint.py (zero_int8 config)"
        )
    on_tpu = jax.default_backend() == "tpu"
    # tp-axis A/B: shard the model over ALL visible chips on the
    # tensor axis with sequence-parallel activations between the TP
    # boundaries; --collective-matmul additionally fuses the boundary
    # collectives into ppermute-ring matmuls (ops/collective_matmul).
    # On a one-chip host the flags still run (identity collectives) so
    # the code path and the distinct metric key are exercised.
    tp = len(jax.devices()) if seq_parallel else 1
    default_seq = SEQ if on_tpu else 128
    seq = min(seq or default_seq, default_seq if not on_tpu else 1 << 20)
    # long-context configs shrink the batch to fit and pay ITERS down
    # (the S^2 attention makes each step long enough to amortize RTT)
    default_batch = (
        BATCH if seq <= 2048 else max(1, BATCH * SEQ // (4 * seq))
    )
    batch = batch or default_batch
    iters = ITERS if seq <= 2048 else max(8, ITERS * SEQ // seq)
    # head_dim = hidden/heads = 128 = the MXU lane width. hd=64 pads
    # every attention operand to 128 lanes and wastes half the MXU —
    # measured 27 ms/step slower on this exact model. TPU-first model
    # configs should keep head_dim a multiple of 128.
    cfg = GPTConfig(
        vocab_size=32768 if on_tpu else 1024,
        hidden_size=1024 if on_tpu else 128,
        num_layers=8 if on_tpu else 2,
        num_attention_heads=8 if on_tpu else 4,
        max_position_embeddings=seq if on_tpu else 128,
        hidden_dropout=dropout,
        attention_dropout=dropout,
        tensor_parallel_size=tp,
        sequence_parallel=seq_parallel,
        collective_matmul=collective_matmul,
        comm_dtype=comm_dtype if collective_matmul else "fp32",
        checkpoint_activations=remat,
    )
    seq = min(seq, cfg.max_position_embeddings)

    mesh = None
    if tp > 1:
        from rocm_apex_tpu.transformer import parallel_state

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(tp, 1)

    model = GPTModel(cfg)
    opt = MixedPrecisionAdam(1e-4, weight_decay=0.01)
    scaler = LossScaler(loss_scale="dynamic")

    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    if mesh is not None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        # sharded init: each rank draws its own weight shards (rank-
        # folded init); the batch is replicated over the tensor axis
        def local_init(tokens):
            return model.init(jax.random.PRNGKey(1), tokens)

        params32 = jax.jit(
            shard_map(
                local_init, mesh=mesh, in_specs=(P(),), out_specs=P(),
                check_rep=False,
            )
        )(tokens[:1])
    else:
        params32 = model.init(jax.random.PRNGKey(1), tokens[:1])

    if dist_opt:
        # ---- ZeRO-sharded data-parallel training (--dist-opt): the
        # contrib DistributedFusedAdam replaces the replicated
        # MixedPrecisionAdam — each rank feeds its UNREDUCED local
        # grads straight into the transform (no pre-pmean: the
        # reduce-scatter IS the gradient averaging), updates only its
        # 1/dp master/moment shards, and all-gathers fresh params.
        # Optimizer state per chip shrinks by dp; the metric line
        # reports the measured bytes next to step time.
        import numpy as np
        import optax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from rocm_apex_tpu.contrib.optimizers import (
            distributed_fused_adam,
        )

        dp = len(jax.devices())
        batch = max(dp, (batch // dp) * dp)
        tokens = tokens[:batch]
        labels = labels[:batch]
        dmesh = Mesh(np.array(jax.devices()), ("data",))
        dist = distributed_fused_adam(
            1e-4, weight_decay=0.01, allgather_dtype="fp32",
            axis_name="data", comm_dtype=comm_dtype,
        )
        ostate = jax.jit(
            shard_map(
                dist.init, mesh=dmesh, in_specs=(P(),),
                out_specs=P(), check_rep=False,
            )
        )(params32)

        def local_runN_zero(params, ostate, rng, tok_l, lab_l):
            def one(carry, _):
                params, ostate, rng = carry
                rng, step_rng = jax.random.split(rng)

                def loss_fn(p):
                    rngs = (
                        {"dropout": step_rng} if dropout > 0.0 else None
                    )
                    return model.apply(
                        p, tok_l, labels=lab_l, loss_reduction="mean",
                        deterministic=dropout == 0.0, rngs=rngs,
                    )

                loss, grads = jax.value_and_grad(loss_fn)(params)
                updates, ostate2 = dist.update(grads, ostate, params)
                return (
                    optax.apply_updates(params, updates), ostate2, rng
                ), loss

            (params, ostate, rng), losses = jax.lax.scan(
                one, (params, ostate, rng), None, length=iters,
                unroll=2,
            )
            return params, ostate, rng, losses

        # params/ostate are DONATED: the scan consumes and returns them,
        # so the executable updates in place instead of holding both
        # generations live (the donation lint pins this). Only metadata
        # reads of params32 (`.size` for the param count) happen after
        # the first call — those survive buffer deletion.
        runN_z = jax.jit(
            shard_map(
                local_runN_zero, mesh=dmesh,
                in_specs=(P(), P(), P(), P("data"), P("data")),
                out_specs=(P(), P(), P(), P()),
                check_rep=False,
            ),
            donate_argnums=(0, 1),
        )
        rng0 = _dropout_rng0(dropout, on_tpu)
        params_z, ostate, rng0, losses = runN_z(
            params32, ostate, rng0, tokens, labels
        )
        float(losses[-1])  # warmup + sync
        t0 = time.perf_counter()
        params_z, ostate, rng0, losses = runN_z(
            params_z, ostate, rng0, tokens, labels
        )
        loss_val = float(losses[-1])
        dt = (time.perf_counter() - t0) / iters

        n_params = sum(
            int(x.size) for x in jax.tree_util.tree_leaves(params32)
        ) - cfg.vocab_size * cfg.hidden_size
        raw_params = sum(
            int(x.size) for x in jax.tree_util.tree_leaves(params32)
        )
        # sharded leaves leave the shard_map with local (1/dp) shapes:
        # summing them IS the per-chip optimizer footprint. The
        # replicated MixedPrecisionAdam reference holds fp32 master +
        # m + v on every chip (12 bytes/param).
        opt_bytes = sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(ostate)
        )
        repl_bytes = 12 * raw_params
        mb = 1.0 / (1024 * 1024)
        step_flops = monitor.model_flops(
            cfg, batch, seq, n_params=n_params
        )
        mfu = monitor.mfu(step_flops, dt, n_chips=dp)
        suffix = "_dropout" if dropout > 0.0 else ""
        if seq != default_seq:
            suffix += f"_s{seq}"
        if batch != default_batch:
            suffix += f"_b{batch}"
        if remat:
            suffix += "_remat"
        suffix += f"_zero_dp{dp}"
        if comm_dtype != "fp32":
            suffix += f"_{comm_dtype}comm"
        _report(
            f"gpt_train_tokens_per_sec_per_chip{suffix}",
            batch * seq / dt / dp, "tokens/s", mfu / 0.70,
            f"step={dt*1000:.1f}ms loss={loss_val:.4f} mfu={mfu:.3f} "
            f"optimizer state {opt_bytes*mb:.2f} MiB/chip (ZeRO "
            f"dp={dp}; replicated fp32 master+m+v would be "
            f"{repl_bytes*mb:.2f} MiB/chip) dropout={dropout} "
            f"b={batch} s={seq} remat={remat} "
            f"backend={jax.default_backend()}",
        )
        # static comm audit (monitor/audit.py): trace ONE ZeRO step
        # abstractly — no compile, no timing impact — and land the
        # estimated collective wire bytes in the jsonl BENCH output so
        # the --comm-dtype A/B is a first-class metric, not a stderr
        # footnote.
        def _one_zero(params, ostate, rng, tok_l, lab_l):
            rng, step_rng = jax.random.split(rng)

            def loss_fn(p):
                rngs = {"dropout": step_rng} if dropout > 0.0 else None
                return model.apply(
                    p, tok_l, labels=lab_l, loss_reduction="mean",
                    deterministic=dropout == 0.0, rngs=rngs,
                )

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, _ = dist.update(grads, ostate, params)
            return loss

        rep = monitor.audit(
            shard_map(
                _one_zero, mesh=dmesh,
                in_specs=(P(), P(), P(), P("data"), P("data")),
                out_specs=P(), check_rep=False,
            ),
            params_z, ostate, rng0, tokens, labels,
        )
        comm_mib = rep.collective_wire_bytes * mb
        _report(
            f"gpt_comm_payload_mib{suffix}", comm_mib, "MiB", 1.0,
            f"estimated per-step collective wire bytes (ZeRO dp={dp}, "
            f"comm_dtype={comm_dtype}; monitor/audit.py conventions) "
            f"ppermute={rep.count('ppermute')} "
            f"backend={jax.default_backend()}",
        )
        if audit:
            print("audit: one gpt ZeRO train step", file=sys.stderr)
            print(rep.summary(), file=sys.stderr)
        return

    state = opt.init(params32)
    sstate = scaler.init()
    rng0 = _dropout_rng0(dropout, on_tpu)

    def make_one_step(opt):
        # parameterized over the optimizer so --packed-update can run
        # the identical step with PackedOptimizerStep (same
        # init/model/step_and_probe surface as MixedPrecisionAdam)
        def one_step(carry, _):
            state, sstate, rng = carry
            rng, step_rng = jax.random.split(rng)

            def loss_fn(params):
                rngs = {"dropout": step_rng} if dropout > 0.0 else None
                if loss == "naive":
                    # A/B reference: materialize the full (b, s, vocab)
                    # logits, cast fp32, optax CE — the path the model
                    # no longer ships (fused_lm_head + in-op mean
                    # reduction)
                    import optax

                    logits = model.apply(
                        params, tokens,
                        deterministic=dropout == 0.0, rngs=rngs,
                    )
                    ce = optax.softmax_cross_entropy_with_integer_labels(
                        logits.astype(jnp.float32), labels
                    ).mean()
                    return ce * scaler.loss_scale(sstate)
                # fused linear-CE head, mean reduction inside the op:
                # the loss cotangent is a scalar, so the head's dx/dW
                # finish in the forward pass and no logits ever hit HBM
                mean = model.apply(
                    params, tokens, labels=labels, loss_reduction="mean",
                    deterministic=dropout == 0.0, rngs=rngs,
                )
                return mean * scaler.loss_scale(sstate)

            scaled, grads = jax.value_and_grad(loss_fn)(state.model)
            inv_scale = 1.0 / scaler.loss_scale(sstate)
            # probe rides the update pass (and fuses into the dW
            # matmuls); a standalone all_finite(grads) would re-read
            # every gradient
            state2, found_inf = opt.step_and_probe(
                state, grads, grad_scale=inv_scale
            )
            sstate2, _ = scaler.update(sstate, found_inf)
            return (state2, sstate2, rng), scaled * inv_scale

        return one_step

    one_step = make_one_step(opt)

    def local_runN(state, sstate, rng):
        # unroll=2 halves the while-loop bookkeeping between steps
        # (measured -0.9 ms/step) at the cost of one extra body compile
        (state, sstate, rng), losses = jax.lax.scan(
            one_step, (state, sstate, rng), None, length=iters, unroll=2
        )
        return state, sstate, rng, losses

    # (state, sstate) are DONATED into the loop: the optimizer carry is
    # the largest resident buffer set in the program and an un-donated
    # step holds two generations of it live (the donation lint pins
    # this). state.master ALIASES params32 (fp32→fp32 astype is a
    # no-copy view), so every VALUE read of params32 must happen before
    # the first runN call — see the hoist block below; `.size`-only
    # metadata reads survive buffer deletion.
    if mesh is not None:
        runN = jax.jit(
            shard_map(
                local_runN, mesh=mesh,
                in_specs=(P(), P(), P()),
                out_specs=(P(), P(), P(), P()),
                check_rep=False,
            ),
            donate_argnums=(0, 1),
        )
    else:
        runN = jax.jit(local_runN, donate_argnums=(0, 1))

    if audit:
        # static program audit (monitor/audit.py): trace ONE train step
        # abstractly — no compile, no timing impact — and report the
        # collective counts/bytes and dot FLOPs to stderr. The jsonl
        # stdout contract is untouched.
        def _one(state, sstate, rng):
            (_, _, _), scaled = one_step((state, sstate, rng), None)
            return scaled

        target = _one
        if mesh is not None:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            target = shard_map(
                _one, mesh=mesh, in_specs=(P(), P(), P()),
                out_specs=P(), check_rep=False,
            )
        report = monitor.audit(target, state, sstate, rng0)
        print("audit: one gpt train step", file=sys.stderr)
        print(report.summary(), file=sys.stderr)

    if lint:
        # graph-contract lint (monitor/lint.py): the train-step ruleset
        # on ONE abstractly traced step — precision policy for the
        # active compute dtype, no materialized (b·s, vocab) logits on
        # the fused-head path (--loss=naive fails this by design: the
        # naive reference IS the materialization), donated carries,
        # trace stability. Exit 1 on any violation.
        def _one_lint(state, sstate, rng):
            (state, sstate, rng), scaled = one_step(
                (state, sstate, rng), None
            )
            return state, sstate, scaled

        target = _one_lint
        if mesh is not None:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            target = shard_map(
                _one_lint, mesh=mesh, in_specs=(P(), P(), P()),
                out_specs=(P(), P(), P()), check_rep=False,
            )
        subject = monitor.LintSubject.from_fn(
            "gpt_train_step", target, state, sstate, rng0,
            donate_argnums=(0, 1),
        )
        rules = [
            monitor.PrecisionPolicy(
                compute_dtype=str(jnp.dtype(cfg.dtype))
            ),
            monitor.NoMaterialization(
                forbidden_shapes=((batch * seq, cfg.vocab_size),)
                if loss == "fused" and _lint_head_is_chunked(cfg, batch, seq)
                else ()
            ),
            monitor.DonationContract(min_bytes=float(64 << 10)),
            monitor.TraceStability(),
        ]
        lint_report = monitor.run_lint(subject, rules)
        print(lint_report.summary(), file=sys.stderr)
        if not lint_report.ok:
            raise SystemExit(1)

    # ---- donation hoists: state.master aliases params32 (no-copy
    # astype), and the first runN call donates state — so everything
    # below that reads params32 VALUES is computed here, before any
    # donating call. (`.size` reads for the param count are metadata
    # and stay where they are.)
    w_emb = hidden0 = None
    if loss == "fused" and tp == 1:
        from rocm_apex_tpu.ops.linear_xentropy import (
            linear_cross_entropy_mean,
        )

        w_emb = jnp.array(
            params32["params"]["embedding"]["word_embeddings"]["weight"],
            dtype=cfg.dtype,  # forced copy: must outlive the donation
        )
        hidden0 = jax.random.normal(
            jax.random.PRNGKey(3), (batch, seq, cfg.hidden_size),
            cfg.dtype,
        )
    if packed_update:
        from rocm_apex_tpu.optimizers.packed import PackedOptimizerStep

        popt = PackedOptimizerStep("adam", 1e-4, weight_decay=0.01)
        # packed init packs masters into FRESH flat buffers — no alias
        pstate = popt.init(params32)
        grads_fix = jax.tree_util.tree_map(
            lambda p: (p * 1e-3 + 1e-5).astype(cfg.dtype), params32
        )
        # the tree-optimizer master tree aliases params32; deep-copy so
        # the update-phase timing below survives the donating runN calls
        upd_state_tree = jax.tree_util.tree_map(
            jnp.array, opt.init(params32)
        )
        upd_state_packed = popt.init(params32)

    state, sstate, rng0, losses = runN(state, sstate, rng0)
    float(losses[-1])  # warmup + sync (value fetch, not block_until_ready)

    t0 = time.perf_counter()
    state, sstate, rng0, losses = runN(state, sstate, rng0)
    loss_val = float(losses[-1])
    dt = (time.perf_counter() - t0) / iters

    tokens_per_sec = batch * seq / dt
    count_tree = params32
    if tp > 1:
        # sharded leaves report local shapes; count the full model
        # from an abstract tp=1 init (eval_shape: no compute)
        import dataclasses
        import math

        cfg_count = dataclasses.replace(
            cfg, tensor_parallel_size=1, sequence_parallel=False,
            collective_matmul=False,
        )
        count_tree = jax.eval_shape(
            lambda t: GPTModel(cfg_count).init(jax.random.PRNGKey(1), t),
            tokens[:1],
        )
        n_params = sum(
            int(math.prod(x.shape))
            for x in jax.tree_util.tree_leaves(count_tree)
        ) - cfg.vocab_size * cfg.hidden_size
    else:
        n_params = sum(
            int(x.size) for x in jax.tree_util.tree_leaves(count_tree)
        ) - cfg.vocab_size * cfg.hidden_size
    # Model FLOPs, Megatron-style, via the shared accounting module
    # (monitor/flops.py — the one copy of the formula; its docstring
    # carries the Narayanan/PaLM crediting discussion). The tied-head
    # projection trio is real dense MXU work (17.3 ms/step of
    # 94-98%-of-peak on this config); BASELINE.md "MFU crediting"
    # documents both numbers and the driver JSON carries the
    # head-inclusive one, with the sans-head figure on stderr.
    step_flops = monitor.model_flops(cfg, batch, seq, n_params=n_params)
    mfu = monitor.mfu(step_flops, dt, n_chips=tp)
    mfu_sans_head = monitor.mfu(
        monitor.model_flops(cfg, batch, seq, n_params=n_params,
                            include_head=False),
        dt, n_chips=tp,
    )
    # per-chip normalization: the tp-sharded step spreads the same
    # global batch over tp chips
    tokens_per_sec = tokens_per_sec / tp
    # the driver's BASELINE series must never mix configs under one
    # key. The dropout suffix keys on the VALUE, not the default:
    # dropout 0.1 became the default in round 5, and its rows must
    # stay series-comparable with the round-4 `_dropout` side rows
    # (and the un-suffixed key must keep meaning dropout=0.0).
    suffix = "_dropout" if dropout > 0.0 else ""
    if seq != default_seq:
        suffix += f"_s{seq}"
    if batch != default_batch:
        suffix += f"_b{batch}"
    if remat:
        suffix += "_remat"
    if loss != "fused":
        suffix += f"_loss_{loss}"
    if seq_parallel:
        # the tp-axis series gets its own keys: _sp (blocking
        # sequence-parallel collectives) vs _spcm (ring collective
        # matmuls), never mixed with the dp series above
        suffix += ("_spcm" if collective_matmul else "_sp") + f"_tp{tp}"
    if comm_dtype != "fp32":
        suffix += f"_{comm_dtype}comm"

    # head share: fwd+bwd of the fused LM head + CE alone, on a bench-
    # shaped hidden batch against the real tied table — the number the
    # in-model `jax.named_scope("lm_head_loss")` annotation attributes
    # in profiles, measured here so BENCH_r*.json records can track it
    # without a profiler run. Skipped under --seq-parallel: the tied
    # table is then a vocab shard per rank and the standalone replay
    # would measure a different (1/tp) head.
    head_ms = None
    if loss == "fused" and tp == 1:
        # w_emb/hidden0 were hoisted above the first donating runN call

        def head_step(carry):
            h, acc = carry
            l, (gh, gw) = jax.value_and_grad(
                lambda h, w: linear_cross_entropy_mean(
                    h, w, labels, None, cfg.label_smoothing,
                    cfg.ignore_index, cfg.lm_head_chunk_size,
                ),
                (0, 1),
            )(h, w_emb)
            # single-column reads force both grads without paying a
            # full extra sweep inside the timed region
            tot = (
                l
                + jnp.sum(gh[..., 0].astype(jnp.float32))
                + jnp.sum(gw[:, 0].astype(jnp.float32))
            )
            return h + (tot * 1e-30).astype(h.dtype), acc + tot

        head_ms = _timed_scan(head_step, (hidden0, jnp.float32(0)), iters)
        print(
            f"lm_head_loss: {head_ms:.2f} ms fwd+bwd "
            f"({100.0 * head_ms / (dt * 1000):.1f}% of step)",
            file=sys.stderr,
        )
    _report(
        f"gpt_train_tokens_per_sec_per_chip{suffix}", tokens_per_sec,
        "tokens/s", mfu / 0.70,
        f"step={dt*1000:.1f}ms loss={loss_val:.4f} mfu={mfu:.3f} "
        f"(sans-head crediting: {mfu_sans_head:.3f}) "
        + (f"head={head_ms:.2f}ms " if head_ms is not None else "")
        + f"dropout={dropout} b={batch} s={seq} remat={remat} "
        f"loss_impl={loss} backend={jax.default_backend()}"
        + (
            f" seq_parallel=True collective_matmul={collective_matmul} "
            f"tp={tp}"
            if seq_parallel
            else ""
        ),
    )
    if audit:
        # the same traced report that printed to stderr, landed in the
        # jsonl output: estimated per-step collective wire bytes
        _report(
            f"gpt_comm_payload_mib{suffix}",
            report.collective_wire_bytes / (1024 * 1024), "MiB", 1.0,
            f"estimated per-step collective wire bytes "
            f"(comm_dtype={comm_dtype}; monitor/audit.py conventions) "
            f"ppermute={report.count('ppermute')} "
            f"backend={jax.default_backend()}",
        )

    if packed_update:
        # ---- packed-buffer optimizer A/B (--packed-update): rerun the
        # IDENTICAL train loop with PackedOptimizerStep (one fused
        # unscale+probe+Adam pass per dtype buffer, masters/moments
        # held packed in the carry) against the MixedPrecisionAdam
        # baseline just measured, then isolate the update phase and the
        # traced program size so the three claims — step time, update
        # share, O(dtype-groups) equations — each get their own number.
        # popt/pstate/grads_fix/upd states were hoisted above the first
        # donating runN call (they read params32 values)
        one_step_p = make_one_step(popt)

        def local_runN_p(state, sstate, rng):
            (state, sstate, rng), losses = jax.lax.scan(
                one_step_p, (state, sstate, rng), None, length=iters,
                unroll=2,
            )
            return state, sstate, rng, losses

        runN_p = jax.jit(local_runN_p, donate_argnums=(0, 1))
        pstate, psstate, prng, plosses = runN_p(
            pstate, scaler.init(), rng0
        )
        ploss_val = float(plosses[-1])  # warmup + sync
        # interleaved best-of-5: tree and packed alternate inside the
        # same wall-clock window so host-load drift (which dominates a
        # ~600 ms CPU step, observed +-10% run to run against a true
        # per-step delta under 0.1%) cancels instead of landing on one
        # side; both sides get the same sample count from the same
        # window, and best-of estimates each program's quiet-host time
        dt_tree = float("inf")
        dt_packed = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            state, sstate, rng0, losses = runN(state, sstate, rng0)
            float(losses[-1])
            dt_tree = min(dt_tree, (time.perf_counter() - t0) / iters)
            t0 = time.perf_counter()
            pstate, psstate, prng, plosses = runN_p(
                pstate, psstate, prng
            )
            ploss_val = float(plosses[-1])
            dt_packed = min(dt_packed, (time.perf_counter() - t0) / iters)

        # update-phase share: the bare optimizer step on fixed grads
        # (bench_optim idiom), tree vs packed, outside the fwd/bwd

        def upd_tree(carry):
            s, g = carry
            s2, _ = opt.step_and_probe(s, g, grad_scale=1.0)
            return s2, g

        def upd_packed(carry):
            s, g = carry
            s2, _ = popt.step_and_probe(s, g, grad_scale=1.0)
            return s2, g

        ms_upd_tree = _timed_scan(
            upd_tree, (upd_state_tree, grads_fix), iters
        )
        ms_upd_packed = _timed_scan(
            upd_packed, (upd_state_packed, grads_fix), iters
        )

        # traced-program size of the bare update (monitor/audit.py
        # equation count): the packed step is O(dtype-groups), the
        # tree step O(leaves) — the fusion-granularity claim, printed
        # here and pinned by tests/L0/test_packed_optimizers.py
        rep_tree = monitor.audit(
            lambda s, g: opt.step_and_probe(s, g, grad_scale=1.0),
            upd_state_tree, grads_fix,
        )
        rep_packed = monitor.audit(
            lambda s, g: popt.step_and_probe(s, g, grad_scale=1.0),
            upd_state_packed, grads_fix,
        )
        n_leaves = len(jax.tree_util.tree_leaves(params32))
        print(
            f"packed A/B: step {dt_packed*1000:.1f} ms vs tree "
            f"{dt_tree*1000:.1f} ms; update phase {ms_upd_packed:.2f} ms "
            f"({100.0 * ms_upd_packed / (dt_packed * 1000):.1f}% of "
            f"step) vs tree {ms_upd_tree:.2f} ms "
            f"({100.0 * ms_upd_tree / (dt_tree * 1000):.1f}%); update "
            f"equations {int(rep_packed.eqn_count)} (packed, "
            f"{n_leaves}-leaf tree) vs {int(rep_tree.eqn_count)} "
            f"(tree-fused)",
            file=sys.stderr,
        )
        _report(
            f"gpt_train_tokens_per_sec_per_chip{suffix}_packed",
            batch * seq / dt_packed, "tokens/s", dt_tree / dt_packed,
            f"step={dt_packed*1000:.1f}ms loss={ploss_val:.4f} "
            f"update={ms_upd_packed:.2f}ms "
            f"(tree {ms_upd_tree:.2f}ms) eqns={int(rep_packed.eqn_count)} "
            f"(tree {int(rep_tree.eqn_count)}, {n_leaves} leaves) "
            f"vs_baseline = tree_step/packed_step "
            f"backend={jax.default_backend()}",
        )


if __name__ == "__main__":
    # driver contract: plain `python bench.py` = the flagship GPT line.
    # `python bench.py rn50|bert` measures the other BASELINE.json
    # configs (results recorded in BASELINE.md). `--dropout=R` on the
    # gpt/bert benches measures the TRAINING config (attention dropout
    # through the in-kernel flash dropout, hidden dropout through the
    # fused LN-dropout path).
    benches = {
        "gpt": main,
        "serve": bench_serve,
        "rn50": bench_rn50,
        "bert": bench_bert,
        "attn": bench_attn,
        "fmha": bench_fmha,
        "optim": bench_optim,
        "ln": bench_ln,
    }
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    kwargs = {}
    for a in sys.argv[1:]:
        if a.startswith("--dropout="):
            kwargs["dropout"] = float(a.split("=", 1)[1])
        elif a.startswith("--batch="):
            kwargs["batch"] = int(a.split("=", 1)[1])
        elif a.startswith("--seq="):
            kwargs["seq"] = int(a.split("=", 1)[1])
        elif a == "--remat":
            kwargs["remat"] = True
        elif a == "--seq-parallel":
            kwargs["seq_parallel"] = True
        elif a == "--collective-matmul":
            kwargs["collective_matmul"] = True
        elif a == "--audit":
            kwargs["audit"] = True
        elif a == "--lint":
            kwargs["lint"] = True
        elif a.startswith("--loss="):
            kwargs["loss"] = a.split("=", 1)[1]
        elif a.startswith("--budget="):
            kwargs["budget"] = int(a.split("=", 1)[1])
        elif a == "--whole-prompt":
            kwargs["whole_prompt"] = True
        elif a.startswith("--trace="):
            kwargs["trace"] = a.split("=", 1)[1]
        elif a == "--paged":
            kwargs["paged"] = True
        elif a.startswith("--page-size="):
            kwargs["page_size"] = int(a.split("=", 1)[1])
        elif a.startswith("--kv-dtype="):
            kwargs["kv_dtype"] = a.split("=", 1)[1]
        elif a == "--shared-prefix":
            kwargs["shared_prefix"] = True
        elif a.startswith("--spec-k="):
            kwargs["spec_k"] = int(a.split("=", 1)[1])
        elif a.startswith("--chaos="):
            kwargs["chaos"] = int(a.split("=", 1)[1])
        elif a == "--slo":
            kwargs["slo"] = True
        elif a.startswith("--metrics-port="):
            kwargs["metrics_port"] = int(a.split("=", 1)[1])
        elif a.startswith("--replicas="):
            kwargs["replicas"] = int(a.split("=", 1)[1])
        elif a.startswith("--tp="):
            kwargs["tp"] = int(a.split("=", 1)[1])
        elif a == "--disagg":
            kwargs["disagg"] = True
        elif a.startswith("--adapters="):
            kwargs["adapters"] = int(a.split("=", 1)[1])
        elif a.startswith("--ranks="):
            kwargs["ranks"] = a.split("=", 1)[1]
        elif a == "--dist-opt":
            kwargs["dist_opt"] = True
        elif a.startswith("--comm-dtype="):
            kwargs["comm_dtype"] = a.split("=", 1)[1]
        elif a == "--packed-update":
            kwargs["packed_update"] = True
        elif a.startswith("--fused="):
            kwargs["fused"] = bool(int(a.split("=", 1)[1]))
        elif a.startswith("--"):
            # a typoed flag must not silently measure the wrong config
            raise SystemExit(f"unknown flag {a!r}")
    which = args[0] if args else "gpt"
    if which not in benches:
        raise SystemExit(
            f"unknown benchmark {which!r}; choose from {sorted(benches)}"
        )
    if "dropout" in kwargs and which not in ("gpt", "bert"):
        raise SystemExit(f"--dropout applies to gpt/bert, not {which!r}")
    if ("batch" in kwargs or "remat" in kwargs) and which not in (
        "gpt", "bert"
    ):
        raise SystemExit("--batch/--remat apply to the gpt/bert benches")
    if "seq" in kwargs and which != "gpt":
        raise SystemExit("--seq applies to the gpt bench")
    if "loss" in kwargs and which != "gpt":
        raise SystemExit("--loss applies to the gpt bench")
    if "audit" in kwargs and which != "gpt":
        raise SystemExit("--audit applies to the gpt bench")
    if "lint" in kwargs and which != "gpt":
        raise SystemExit("--lint applies to the gpt bench")
    if (
        "seq_parallel" in kwargs or "collective_matmul" in kwargs
    ) and which != "gpt":
        raise SystemExit(
            "--seq-parallel/--collective-matmul apply to the gpt bench"
        )
    if (
        "budget" in kwargs or "whole_prompt" in kwargs
        or "trace" in kwargs or "paged" in kwargs
        or "page_size" in kwargs or "kv_dtype" in kwargs
        or "shared_prefix" in kwargs or "spec_k" in kwargs
        or "chaos" in kwargs or "slo" in kwargs
        or "metrics_port" in kwargs or "replicas" in kwargs
        or "tp" in kwargs or "disagg" in kwargs
        or "adapters" in kwargs or "ranks" in kwargs
    ) and which != "serve":
        raise SystemExit(
            "--budget/--whole-prompt/--trace/--paged/--page-size/"
            "--kv-dtype/--shared-prefix/--spec-k/--chaos/--slo/"
            "--metrics-port/--replicas/--tp/--disagg/--adapters/"
            "--ranks apply to the serve bench"
        )
    if kwargs.get("adapters", 1) < 1:
        raise SystemExit("--adapters takes a pool size N >= 1")
    if "ranks" in kwargs and "adapters" not in kwargs:
        raise SystemExit("--ranks requires --adapters")
    if "adapters" in kwargs and any(
        k in kwargs
        for k in ("whole_prompt", "shared_prefix", "spec_k", "paged",
                  "kv_dtype", "page_size", "replicas", "tp", "disagg",
                  "slo", "trace")
    ):
        raise SystemExit(
            "--adapters runs its own single-model A/B (or, with "
            "--chaos, the tenant-isolation scenario); it composes "
            "with --chaos/--budget/--metrics-port only"
        )
    if kwargs.get("tp", 2) < 2:
        raise SystemExit("--tp takes a tensor-parallel width N >= 2")
    if "tp" in kwargs and any(
        k not in ("tp", "budget", "page_size") for k in kwargs
    ):
        raise SystemExit(
            "--tp runs its own equal-chip-count paged A/B; it "
            "composes with --budget/--page-size only"
        )
    if kwargs.get("disagg") and any(
        k in kwargs
        for k in ("whole_prompt", "shared_prefix", "spec_k",
                  "slo", "metrics_port", "trace", "paged", "kv_dtype",
                  "tp")
    ):
        raise SystemExit(
            "--disagg runs its own equal-chip-count fleet A/B; it "
            "composes with --replicas/--budget/--page-size/--chaos "
            "only (--chaos adds the fleet-trace observability pass)"
        )
    if kwargs.get("spec_k", 0) < 0:
        raise SystemExit("--spec-k must be >= 0")
    if kwargs.get("chaos", 0) < 0:
        raise SystemExit("--chaos takes a seed >= 0")
    if kwargs.get("metrics_port", 0) < 0:
        raise SystemExit("--metrics-port takes a port >= 0 (0 = ephemeral)")
    if kwargs.get("replicas", 2) < 2:
        raise SystemExit("--replicas takes a fleet size N >= 2")
    if "replicas" in kwargs and (
        kwargs.get("whole_prompt") or kwargs.get("shared_prefix")
        or "spec_k" in kwargs or kwargs.get("slo")
    ):
        raise SystemExit(
            "--replicas runs the fleet pass on the mixed workload; it "
            "composes with --chaos/--paged/--metrics-port, not with "
            "--whole-prompt/--shared-prefix/--spec-k/--slo"
        )
    if ("slo" in kwargs or "metrics_port" in kwargs) and (
        kwargs.get("shared_prefix") or "spec_k" in kwargs
        or (
            kwargs.get("paged") and "chaos" not in kwargs
            and "replicas" not in kwargs
        )
    ):
        raise SystemExit(
            "--slo/--metrics-port instrument the mixed-workload serve "
            "pass (plain or --chaos); they do not compose with "
            "--shared-prefix/--spec-k/--paged-without-chaos"
        )
    if "chaos" in kwargs and (
        kwargs.get("shared_prefix") or "spec_k" in kwargs
        or kwargs.get("whole_prompt")
    ):
        raise SystemExit(
            "--chaos runs its own serving pass; it does not compose "
            "with --whole-prompt/--shared-prefix/--spec-k"
        )
    if "dist_opt" in kwargs and which != "gpt":
        raise SystemExit("--dist-opt applies to the gpt bench")
    if "comm_dtype" in kwargs and which != "gpt":
        raise SystemExit("--comm-dtype applies to the gpt bench")
    if "packed_update" in kwargs and which != "gpt":
        raise SystemExit("--packed-update applies to the gpt bench")
    if kwargs.get("dist_opt") and kwargs.get("seq_parallel"):
        raise SystemExit(
            "--dist-opt shards the optimizer over the data axis; it "
            "does not compose with --seq-parallel (tensor axis)"
        )
    if kwargs.get("kv_dtype") not in (None, "int8"):
        raise SystemExit(
            f"--kv-dtype={kwargs['kv_dtype']!r}: only int8 is a "
            "quantized cache dtype (omit the flag for the model dtype)"
        )
    if (
        "page_size" in kwargs or "kv_dtype" in kwargs
    ) and not (kwargs.get("paged") or kwargs.get("shared_prefix")):
        raise SystemExit(
            "--page-size/--kv-dtype require --paged (or --shared-prefix)"
        )
    if "fused" in kwargs and which != "rn50":
        raise SystemExit("--fused applies to the rn50 bench")
    if kwargs.get("fused") and jax.default_backend() != "tpu":
        # a flag must not silently measure the wrong config: the fused
        # kernel path is TPU-only (interpret mode would measure noise)
        raise SystemExit("--fused=1 requires the TPU backend")
    benches[which](**kwargs)
