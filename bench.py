"""Driver benchmark: one JSON line on stdout.

Measures the flagship config on whatever single chip is available: a
Megatron-style GPT train step under the O5/amp-O2 recipe — bf16 model
params computing with Pallas flash attention + fused CE, fp32 masters
updated by the XLA-tree-fused mixed-precision Adam (optimizers/mixed.py
— see its header for why tree fusion, not buffer packing, is the TPU
fast path), dynamic loss scaling with jit-safe skip-step — reporting
tokens/sec/chip.

Timing notes:
* ITERS steps run inside ONE dispatch via `lax.scan` — the axon tunnel
  adds tens of ms of per-dispatch latency that real multi-step training
  does not pay;
* on the tunnel platform `block_until_ready` does NOT synchronize; the
  timed region ends with a scalar value fetch.

The reference publishes no numbers (SURVEY.md §6, BASELINE.json
"published": {}), so ``vs_baseline`` is the ratio against BASELINE.md's
north-star bar (70% MFU): vs_baseline = MFU / 0.70.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

from rocm_apex_tpu.amp import LossScaler
from rocm_apex_tpu.models.gpt import GPTConfig, GPTModel, gpt_loss_fn
from rocm_apex_tpu.optimizers.mixed import MixedPrecisionAdam

BATCH = 16
SEQ = 1024
# one warmup runN (compile + state settle) then one timed. 50 steps per
# dispatch: the axon tunnel's value-fetch round-trip is ~100 ms, so at
# N steps the wall clock over-reports each step by ~100/N ms — real
# training fetches nothing per step.
ITERS = 50


def peak_flops_per_chip() -> float:
    """Best-effort bf16 peak for the local chip; CPU fallback is nominal."""
    kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    table = {
        "v6e": 918e12,
        "v6": 918e12,
        "v5p": 459e12,
        "v5 lite": 197e12,
        "v5e": 197e12,
        "v5": 459e12,
        "v4": 275e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 1e12


def _report(metric, value, unit, vs_baseline, extra=""):
    print(extra, file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 1),
                "unit": unit,
                "vs_baseline": round(vs_baseline, 4),
            }
        )
    )


def bench_rn50():
    """BASELINE.json config 2: ResNet-50, O5 recipe (bf16 + fp32
    masters via amp.initialize) + FusedAdam, images/sec/chip.
    DDP-equivalent gradient psum degenerates on one chip (the
    multi-chip path is exercised by tests/L0/test_parallel.py)."""
    import optax

    from rocm_apex_tpu import amp, models
    from rocm_apex_tpu.optimizers import FusedAdam

    on_tpu = jax.default_backend() == "tpu"
    batch = 128 if on_tpu else 4  # b128 beats b64 by 16% img/s on v5e
    size = 224 if on_tpu else 32
    iters = 20 if on_tpu else 2
    # the policy's compute dtype threads through the model definition
    # (SURVEY §7: flax-style dtype IS the O-level cast_model_type);
    # without it every conv and feature map runs fp32 — measured 97.7
    # vs 53.1 ms per step on v5e. BN params stay fp32 via amp.initialize
    # (keep_batchnorm_fp32) and flax accumulates BN stats in fp32.
    model = models.resnet50(
        num_classes=1000,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
    )
    x0 = jnp.zeros((batch, size, size, 3))
    variables = model.init(jax.random.PRNGKey(0), x0)
    params, batch_stats = variables["params"], variables["batch_stats"]
    optimizer = FusedAdam(1e-3, weight_decay=1e-4)
    params, optimizer, amp_state = amp.initialize(
        params, optimizer, opt_level="O5" if on_tpu else "O0"
    )
    opt_state = optimizer.init(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, size, size, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)

    def one_step(carry, _):
        params, batch_stats, opt_state, scaler_states = carry
        st = amp_state.replace(scaler_states=scaler_states)

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats},
                x.astype(jnp.bfloat16 if on_tpu else jnp.float32),
                mutable=["batch_stats"],
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y
            ).mean()
            return amp.scale_loss(ce, st), (mut["batch_stats"], ce)

        (_, (bs2, ce)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        grads, found_inf = amp.unscale_grads(grads, st)
        st2, skip = amp.update_scale(st, found_inf)
        updates, opt2 = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params = amp.skip_step(skip, new_params, params)
        opt2 = amp.skip_step(skip, opt2, opt_state)
        return (new_params, bs2, opt2, st2.scaler_states), ce

    @jax.jit
    def runN(params, batch_stats, opt_state, scaler_states):
        carry, ces = jax.lax.scan(
            one_step,
            (params, batch_stats, opt_state, scaler_states),
            None,
            length=iters,
        )
        return carry, ces

    carry, ces = runN(params, batch_stats, opt_state, amp_state.scaler_states)
    float(ces[-1])
    t0 = time.perf_counter()
    carry, ces = runN(*carry)
    loss = float(ces[-1])
    dt = (time.perf_counter() - t0) / iters
    img_s = batch / dt
    # RN50 train ~ 3 x 4.1 GFLOPs fwd per image at 224x224
    mfu = (12.3e9 * batch / dt) / peak_flops_per_chip()
    _report(
        "rn50_train_images_per_sec_per_chip", img_s, "images/s", mfu / 0.70,
        f"rn50: step={dt*1000:.1f}ms loss={loss:.3f} mfu={mfu:.3f}",
    )


def bench_bert():
    """BASELINE.json config 4: BERT-Large-shaped MLM pretrain step with
    FusedLAMB + fused LayerNorm, tokens/sec/chip. 24L/1024h with
    head_dim 128 (the TPU-first head shape; see main())."""
    from rocm_apex_tpu.models import BertConfig, BertModel
    from rocm_apex_tpu.optimizers import fused_lamb
    from rocm_apex_tpu.utils.tree import path_str

    on_tpu = jax.default_backend() == "tpu"
    # b8 fits since the round-3 kernel work (merged attention backward
    # + one-pass CE shrank the live-buffer set); b16 still exhausts the
    # 16 GB chip (330M params of fp32 LAMB p/m/v + activations)
    batch = 8 if on_tpu else 2
    seq = 512 if on_tpu else 64
    iters = 20 if on_tpu else 2
    cfg = BertConfig(
        vocab_size=30592 if on_tpu else 1024,
        hidden_size=1024 if on_tpu else 64,
        num_layers=24 if on_tpu else 2,
        num_attention_heads=8 if on_tpu else 4,
        ffn_hidden_size=4096 if on_tpu else 128,
        max_position_embeddings=seq,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_parallel_size=1,
    )
    model = BertModel(cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (batch, seq), 0, cfg.vocab_size
    )
    lm_labels = jnp.roll(tokens, 1, axis=1)
    params = model.init(jax.random.PRNGKey(1), tokens[:1])
    flat = jax.tree_util.tree_map_with_path(
        lambda kp, _: not (
            path_str(kp).endswith("bias") or "layernorm" in path_str(kp).lower()
        ),
        params,
    )
    opt = fused_lamb(1e-4, weight_decay=0.01, weight_decay_mask=flat)
    opt_state = opt.init(params)

    def one_step(carry, _):
        params, opt_state = carry

        def loss_fn(p):
            losses, _ = model.apply(p, tokens, lm_labels=lm_labels)
            return jnp.mean(losses)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        params2 = jax.tree_util.tree_map(
            lambda p, u: (p.astype(jnp.float32) + u).astype(p.dtype),
            params,
            updates,
        )
        return (params2, opt_state2), loss

    @jax.jit
    def runN(params, opt_state):
        carry, losses = jax.lax.scan(
            one_step, (params, opt_state), None, length=iters
        )
        return carry, losses

    carry, losses = runN(params, opt_state)
    float(losses[-1])
    t0 = time.perf_counter()
    carry, losses = runN(*carry)
    loss = float(losses[-1])
    dt = (time.perf_counter() - t0) / iters
    tok_s = batch * seq / dt
    n_params = sum(
        int(x.size) for x in jax.tree_util.tree_leaves(params)
    ) - cfg.vocab_size * cfg.hidden_size
    flops = 6.0 * n_params * batch * seq + (
        12.0 * cfg.num_layers * batch * seq * seq * cfg.hidden_size
    )
    mfu = (flops / dt) / peak_flops_per_chip()
    _report(
        "bert_large_train_tokens_per_sec_per_chip", tok_s, "tokens/s",
        mfu / 0.70,
        f"bert: step={dt*1000:.1f}ms loss={loss:.3f} mfu={mfu:.3f}",
    )


def main():
    on_tpu = jax.default_backend() == "tpu"
    # head_dim = hidden/heads = 128 = the MXU lane width. hd=64 pads
    # every attention operand to 128 lanes and wastes half the MXU —
    # measured 27 ms/step slower on this exact model. TPU-first model
    # configs should keep head_dim a multiple of 128.
    cfg = GPTConfig(
        vocab_size=32768 if on_tpu else 1024,
        hidden_size=1024 if on_tpu else 128,
        num_layers=8 if on_tpu else 2,
        num_attention_heads=8 if on_tpu else 4,
        max_position_embeddings=SEQ if on_tpu else 128,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_parallel_size=1,
    )
    seq = min(SEQ, cfg.max_position_embeddings)

    model = GPTModel(cfg)
    opt = MixedPrecisionAdam(1e-4, weight_decay=0.01)
    scaler = LossScaler(loss_scale="dynamic")

    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (BATCH, seq), 0, cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    params32 = model.init(jax.random.PRNGKey(1), tokens[:1])
    state = opt.init(params32)
    sstate = scaler.init()

    def one_step(carry, _):
        state, sstate = carry

        def loss_fn(params):
            losses = model.apply(params, tokens, labels=labels)
            return gpt_loss_fn(losses) * scaler.loss_scale(sstate)

        scaled, grads = jax.value_and_grad(loss_fn)(state.model)
        inv_scale = 1.0 / scaler.loss_scale(sstate)
        # probe rides the update pass (and fuses into the dW matmuls);
        # a standalone all_finite(grads) would re-read every gradient
        state2, found_inf = opt.step_and_probe(
            state, grads, grad_scale=inv_scale
        )
        sstate2, _ = scaler.update(sstate, found_inf)
        return (state2, sstate2), scaled * inv_scale

    @jax.jit
    def runN(state, sstate):
        (state, sstate), losses = jax.lax.scan(
            one_step, (state, sstate), None, length=ITERS
        )
        return state, sstate, losses

    state, sstate, losses = runN(state, sstate)
    float(losses[-1])  # warmup + sync (value fetch, not block_until_ready)

    t0 = time.perf_counter()
    state, sstate, losses = runN(state, sstate)
    loss = float(losses[-1])
    dt = (time.perf_counter() - t0) / ITERS

    tokens_per_sec = BATCH * seq / dt
    n_params = sum(
        int(x.size) for x in jax.tree_util.tree_leaves(params32)
    ) - cfg.vocab_size * cfg.hidden_size
    model_flops = 6.0 * n_params * BATCH * seq + (
        12.0 * cfg.num_layers * BATCH * seq * seq * cfg.hidden_size
    )
    mfu = (model_flops / dt) / peak_flops_per_chip()
    _report(
        "gpt_train_tokens_per_sec_per_chip", tokens_per_sec, "tokens/s",
        mfu / 0.70,
        f"step={dt*1000:.1f}ms loss={loss:.4f} mfu={mfu:.3f} "
        f"backend={jax.default_backend()}",
    )


if __name__ == "__main__":
    # driver contract: plain `python bench.py` = the flagship GPT line.
    # `python bench.py rn50|bert` measures the other BASELINE.json
    # configs (results recorded in BASELINE.md).
    benches = {"gpt": main, "rn50": bench_rn50, "bert": bench_bert}
    which = sys.argv[1] if len(sys.argv) > 1 else "gpt"
    if which not in benches:
        raise SystemExit(
            f"unknown benchmark {which!r}; choose from {sorted(benches)}"
        )
    benches[which]()
