"""Driver benchmark: one JSON line on stdout.

Measures the flagship config on whatever single chip is available: a
Megatron-style GPT train step under the O5/amp-O2 recipe — bf16 model
params computing with Pallas flash attention + fused CE, fp32 masters
updated by the XLA-tree-fused mixed-precision Adam (optimizers/mixed.py
— see its header for why tree fusion, not buffer packing, is the TPU
fast path), dynamic loss scaling with jit-safe skip-step — reporting
tokens/sec/chip.

Timing notes:
* ITERS steps run inside ONE dispatch via `lax.scan` — the axon tunnel
  adds tens of ms of per-dispatch latency that real multi-step training
  does not pay;
* on the tunnel platform `block_until_ready` does NOT synchronize; the
  timed region ends with a scalar value fetch.

The reference publishes no numbers (SURVEY.md §6, BASELINE.json
"published": {}), so ``vs_baseline`` is the ratio against BASELINE.md's
north-star bar (70% MFU): vs_baseline = MFU / 0.70.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

from rocm_apex_tpu.amp import LossScaler
from rocm_apex_tpu.models.gpt import GPTConfig, GPTModel, gpt_loss_fn
from rocm_apex_tpu.optimizers.mixed import MixedPrecisionAdam

BATCH = 16
SEQ = 1024
# one warmup runN (compile + state settle) then one timed. 50 steps per
# dispatch: the axon tunnel's value-fetch round-trip is ~100 ms, so at
# N steps the wall clock over-reports each step by ~100/N ms — real
# training fetches nothing per step.
ITERS = 50


def peak_flops_per_chip() -> float:
    """Best-effort bf16 peak for the local chip; CPU fallback is nominal."""
    kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    table = {
        "v6e": 918e12,
        "v6": 918e12,
        "v5p": 459e12,
        "v5 lite": 197e12,
        "v5e": 197e12,
        "v5": 459e12,
        "v4": 275e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 1e12


def main():
    on_tpu = jax.default_backend() == "tpu"
    # head_dim = hidden/heads = 128 = the MXU lane width. hd=64 pads
    # every attention operand to 128 lanes and wastes half the MXU —
    # measured 27 ms/step slower on this exact model. TPU-first model
    # configs should keep head_dim a multiple of 128.
    cfg = GPTConfig(
        vocab_size=32768 if on_tpu else 1024,
        hidden_size=1024 if on_tpu else 128,
        num_layers=8 if on_tpu else 2,
        num_attention_heads=8 if on_tpu else 4,
        max_position_embeddings=SEQ if on_tpu else 128,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_parallel_size=1,
    )
    seq = min(SEQ, cfg.max_position_embeddings)

    model = GPTModel(cfg)
    opt = MixedPrecisionAdam(1e-4, weight_decay=0.01)
    scaler = LossScaler(loss_scale="dynamic")

    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (BATCH, seq), 0, cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    params32 = model.init(jax.random.PRNGKey(1), tokens[:1])
    state = opt.init(params32)
    sstate = scaler.init()

    def one_step(carry, _):
        state, sstate = carry

        def loss_fn(params):
            losses = model.apply(params, tokens, labels=labels)
            return gpt_loss_fn(losses) * scaler.loss_scale(sstate)

        scaled, grads = jax.value_and_grad(loss_fn)(state.model)
        inv_scale = 1.0 / scaler.loss_scale(sstate)
        # probe rides the update pass (and fuses into the dW matmuls);
        # a standalone all_finite(grads) would re-read every gradient
        state2, found_inf = opt.step_and_probe(
            state, grads, grad_scale=inv_scale
        )
        sstate2, _ = scaler.update(sstate, found_inf)
        return (state2, sstate2), scaled * inv_scale

    @jax.jit
    def runN(state, sstate):
        (state, sstate), losses = jax.lax.scan(
            one_step, (state, sstate), None, length=ITERS
        )
        return state, sstate, losses

    state, sstate, losses = runN(state, sstate)
    float(losses[-1])  # warmup + sync (value fetch, not block_until_ready)

    t0 = time.perf_counter()
    state, sstate, losses = runN(state, sstate)
    loss = float(losses[-1])
    dt = (time.perf_counter() - t0) / ITERS

    tokens_per_sec = BATCH * seq / dt
    n_params = sum(
        int(x.size) for x in jax.tree_util.tree_leaves(params32)
    ) - cfg.vocab_size * cfg.hidden_size
    model_flops = 6.0 * n_params * BATCH * seq + (
        12.0 * cfg.num_layers * BATCH * seq * seq * cfg.hidden_size
    )
    mfu = (model_flops / dt) / peak_flops_per_chip()
    print(
        f"step={dt*1000:.1f}ms loss={loss:.4f} mfu={mfu:.3f} "
        f"backend={jax.default_backend()}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "gpt_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / 0.70, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
