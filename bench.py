"""Driver benchmark: one JSON line on stdout.

Measures the flagship config on whatever single chip is available: a
Megatron-style GPT train step — bf16 compute + fp32 masters (the
O5/amp-O2 recipe), fused-Adam Pallas update, dynamic loss scaling —
reporting tokens/sec/chip. The reference publishes no numbers
(SURVEY.md §6, BASELINE.json "published": {}), so ``vs_baseline`` is
the ratio against the model-FLOPs roofline of the chip (i.e. MFU),
the target BASELINE.md sets (>=70% MFU north star).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

from rocm_apex_tpu.models.gpt import GPTConfig, GPTModel, gpt_loss_fn
from rocm_apex_tpu.optimizers import fused_adam
from rocm_apex_tpu.amp import LossScaler
from rocm_apex_tpu.optimizers._common import tree_where

BATCH = 8
SEQ = 1024
WARMUP = 2
ITERS = 10


def peak_flops_per_chip() -> float:
    """Best-effort bf16 peak for the local chip; CPU fallback is tiny."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu").lower()
    table = {
        "v6e": 918e12,
        "v6": 918e12,
        "v5p": 459e12,
        "v5e": 197e12,
        "v5": 197e12,
        "v4": 275e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 1e12  # CPU / unknown: nominal


def main():
    cfg = GPTConfig(
        vocab_size=32768,
        hidden_size=1024,
        num_layers=8,
        num_attention_heads=16,
        max_position_embeddings=SEQ,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_parallel_size=1,
    )
    if jax.default_backend() != "tpu":
        # keep the CPU smoke run fast
        cfg = GPTConfig(
            vocab_size=1024,
            hidden_size=128,
            num_layers=2,
            num_attention_heads=4,
            max_position_embeddings=128,
            hidden_dropout=0.0,
            attention_dropout=0.0,
            tensor_parallel_size=1,
        )
    seq = min(SEQ, cfg.max_position_embeddings)

    model = GPTModel(cfg)
    optimizer = fused_adam(1e-4, weight_decay=0.01)
    scaler = LossScaler(loss_scale="dynamic")

    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (BATCH, seq), 0, cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(1), tokens[:1])
    opt_state = optimizer.init(params)
    scaler_state = scaler.init()

    @jax.jit
    def step(params, opt_state, scaler_state, tokens, labels):
        def loss_fn(p):
            losses = model.apply(p, tokens, labels=labels)
            return gpt_loss_fn(losses) * scaler.loss_scale(scaler_state)

        scaled, grads = jax.value_and_grad(loss_fn)(params)
        grads, found_inf = scaler.unscale(scaler_state, grads)
        scaler_state2, skip = scaler.update(scaler_state, found_inf)
        updates, opt_state2 = optimizer.update(grads, opt_state, params)
        new_params = jax.tree_util.tree_map(jnp.add, params, updates)
        return (
            tree_where(skip, params, new_params),
            tree_where(skip, opt_state, opt_state2),
            scaler_state2,
            scaled / scaler.loss_scale(scaler_state),
        )

    # NOTE: on the axon tunnel platform block_until_ready does NOT wait
    # for execution — only a value fetch synchronizes. Iterations chain
    # through params, so one final scalar fetch bounds all ITERS steps.
    for _ in range(WARMUP):
        params, opt_state, scaler_state, loss = step(
            params, opt_state, scaler_state, tokens, labels
        )
    float(loss)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        params, opt_state, scaler_state, loss = step(
            params, opt_state, scaler_state, tokens, labels
        )
    float(loss)
    dt = (time.perf_counter() - t0) / ITERS

    tokens_per_sec = BATCH * seq / dt
    # 6 * N_non-embedding * tokens (fwd+bwd) model FLOPs
    n_params = sum(
        x.size for x in jax.tree_util.tree_leaves(params)
    ) - cfg.vocab_size * cfg.hidden_size
    model_flops = 6.0 * n_params * BATCH * seq + (
        # attention score/context matmuls: 12 * b * s^2 * h per layer
        12.0 * cfg.num_layers * BATCH * seq * seq * cfg.hidden_size
    )
    mfu = (model_flops / dt) / peak_flops_per_chip()
    print(
        f"step={dt*1000:.1f}ms loss={float(loss):.4f} mfu={mfu:.3f} "
        f"backend={jax.default_backend()}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "gpt_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / 0.70, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
