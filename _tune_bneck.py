"""Dev driver: isolate the fused-bottleneck kernels at RN50 stage
shapes, time them with scan (cancels the ~100 ms tunnel RTT), and
sweep the block-size knobs.

Usage: python _tune_bneck.py [stage ...] [--sweep]
"""

import sys
import time

import jax
import jax.numpy as jnp

import rocm_apex_tpu.ops.fused_bottleneck as fb

STAGES = {
    "l1": (128, 56, 56, 64, 256),
    "l2": (128, 28, 28, 128, 512),
    "l3": (128, 14, 14, 256, 1024),
    "l4": (128, 7, 7, 512, 2048),
}
ITERS = 30


def scan_time(make_step, init):
    """ms/iter via scan-length differencing (bench.py idiom)."""
    def run(n):
        @jax.jit
        def f(c):
            return jax.lax.scan(lambda c, _: (make_step(c), None),
                                c, None, length=n)[0]
        return f

    f1, f2 = run(ITERS), run(2 * ITERS)
    c = f1(init)
    jax.tree_util.tree_map(
        lambda t: float(t.reshape(-1)[0].astype(jnp.float32)), c)
    c = f2(init)
    float(jax.tree_util.tree_leaves(c)[0].reshape(-1)[0].astype(jnp.float32))

    def best(f):
        ts = []
        for _ in range(2):
            t0 = time.perf_counter()
            r = f(init)
            float(jax.tree_util.tree_leaves(r)[0].reshape(-1)[0]
                  .astype(jnp.float32))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    return max(best(f2) - best(f1), 1e-9) / ITERS * 1000


def bench_stage(st):
    n, h, w_, c, cout = STAGES[st]
    m = n * h * w_
    key = jax.random.PRNGKey(0)
    x4 = (jax.random.normal(key, (n, h, w_, c)) * 0.5).astype(jnp.bfloat16)
    w3 = (jax.random.normal(key, (3, 3, c, c)) * 0.05).astype(jnp.bfloat16)
    w1 = (jax.random.normal(key, (c, cout)) * 0.05).astype(jnp.bfloat16)
    a = jnp.ones((c,), jnp.float32)
    b = jnp.zeros((c,), jnp.float32)
    mu = jnp.zeros((c,), jnp.float32)
    rs = jnp.ones((c,), jnp.float32)
    gbmap = m * c * 2 / 1e9

    fb31 = lambda x: fb.conv3x3_bn_act(x, w3, a, b, stats=True)
    def step_c3f(x):
        y, (s1, s2) = fb31(x)
        return x + (s1[0] * 1e-30).astype(x.dtype)
    t = scan_time(step_c3f, x4)
    print(f"{st} conv3x3 fwd: {t:7.3f} ms ({2*gbmap/(t/1e3):5.0f} GB/s)")

    def step_c3x(x):
        y = jax.lax.conv_general_dilated(
            x, w3, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return x + (jnp.sum(y[0, 0, 0, :1]) * 1e-30).astype(x.dtype)
    t = scan_time(step_c3x, x4)
    print(f"{st} conv3x3 XLA: {t:7.3f} ms ({2*gbmap/(t/1e3):5.0f} GB/s)")

    def step_c3b(x):
        g, dw, r1, r2 = fb.conv3x3_bn_act_bwd(
            x, w3, x, None, (a, b), (mu, rs))
        return x + (r1[:1] * 1e-30).astype(x.dtype)
    t = scan_time(step_c3b, x4)
    print(f"{st} conv3x3 bwd: {t:7.3f} ms ({3*gbmap/(t/1e3):5.0f} GB/s)")

    x2 = x4.reshape(m, c)
    def step_m1(x):
        y, (s1, s2) = fb.conv1x1_bn_act(x, w1, a, b, stats=True)
        return x + (s1[0] * 1e-30).astype(x.dtype)
    t = scan_time(step_m1, x2)
    tr = gbmap * (1 + cout / c)
    print(f"{st} conv1x1 fwd: {t:7.3f} ms ({tr/(t/1e3):5.0f} GB/s)")

    e_big = jnp.ones((m, cout), jnp.bfloat16)
    def step_m1b(e):
        g, dw, r1, r2 = fb.conv1x1_bn_act_bwd(
            e, w1, x2, prologue=(a, b), reduce_stats=(mu, rs))
        return e + (r1[:1] * 1e-30).astype(e.dtype)
    t = scan_time(step_m1b, e_big)
    tr = gbmap * (2 + 2 * cout / c)
    print(f"{st} conv1x1 bwd: {t:7.3f} ms ({tr/(t/1e3):5.0f} GB/s)")
    print(flush=True)


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    for kv in (a for a in sys.argv[1:] if a.startswith("--set=")):
        k, v = kv[6:].split(":")
        fb.config[k] = int(v)
    print("config:", fb.config, flush=True)
    for st in args or list(STAGES):
        bench_stage(st)
