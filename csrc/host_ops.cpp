// Host-native runtime ops for rocm_apex_tpu.
//
// TPU-native equivalent of the reference's host-side native layer:
//  * flatten/unflatten of tensor buckets (reference:
//    csrc/flatten_unflatten.cpp, the apex_C extension backing DDP's
//    bucket packing, apex/parallel/distributed.py:13-33). On TPU the
//    DEVICE-side packing belongs to XLA (see optimizers/mixed.py for
//    the measurement); the host-side version remains the fast path for
//    checkpoint IO and input staging of many small arrays.
//  * fast_collate (reference: examples/imagenet/main_amp.py
//    fast_collate + data_prefetcher): uint8 HWC image batches to a
//    normalized float NHWC buffer without a Python-loop per image.
//
// Plain C ABI (no pybind11 in this image); bound via ctypes from
// rocm_apex_tpu/_native/__init__.py with a numpy fallback.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) over up to `threads` std::threads.
template <typename F>
void parallel_for(int64_t n, int threads, F fn) {
  if (n <= 0) return;
  int t = threads;
  if (t > n) t = static_cast<int>(n);
  if (t <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(t);
  for (int k = 0; k < t; ++k) {
    pool.emplace_back([k, t, n, &fn]() {
      for (int64_t i = k; i < n; i += t) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Concatenate n buffers (sizes[i] elements of elem_size bytes) into dst.
void apex_tpu_flatten(const void** srcs, const int64_t* sizes, int64_t n,
                      int64_t elem_size, void* dst, int threads) {
  std::vector<int64_t> offsets(n);
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = off;
    off += sizes[i];
  }
  char* out = static_cast<char*>(dst);
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(out + offsets[i] * elem_size, srcs[i],
                static_cast<size_t>(sizes[i] * elem_size));
  });
}

// Split src back into n buffers.
void apex_tpu_unflatten(const void* src, const int64_t* sizes, int64_t n,
                        int64_t elem_size, void** dsts, int threads) {
  std::vector<int64_t> offsets(n);
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = off;
    off += sizes[i];
  }
  const char* in = static_cast<const char*>(src);
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(dsts[i], in + offsets[i] * elem_size,
                static_cast<size_t>(sizes[i] * elem_size));
  });
}

// n uint8 HWC images -> float32 NHWC batch, normalized (x/255 - mean)/std
// per channel. mean/std may be null (skip normalization, keep 0..255
// like the reference's fast_collate which defers normalization).
void apex_tpu_fast_collate(const uint8_t** imgs, int64_t n, int64_t h,
                           int64_t w, int64_t c, float* dst,
                           const float* mean, const float* std_,
                           int threads) {
  const int64_t hwc = h * w * c;
  parallel_for(n, threads, [&](int64_t i) {
    const uint8_t* src = imgs[i];
    float* out = dst + i * hwc;
    if (mean && std_) {
      for (int64_t p = 0; p < hwc; ++p) {
        const int64_t ch = p % c;
        out[p] = (src[p] * (1.0f / 255.0f) - mean[ch]) / std_[ch];
      }
    } else {
      for (int64_t p = 0; p < hwc; ++p) out[p] = src[p];
    }
  });
}

}  // extern "C"
