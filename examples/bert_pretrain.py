"""BERT pretraining with FusedLAMB + fused LayerNorm.

The BASELINE.md config-4 scenario ("BERT-Large pretrain with FusedLAMB
+ apex.normalization.FusedLayerNorm"; reference:
apex/transformer/testing/standalone_bert.py driven by the L0 BERT
minimal test, run_bert_minimal_test.py). Masked-LM objective on
synthetic data, LAMB with the usual no-decay mask for biases/LN,
data-parallel over the mesh.

CPU smoke:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/bert_pretrain.py --num-layers 2 --hidden-size 64 \
        --num-attention-heads 4 --seq-length 32 --micro-batch-size 2 \
        --train-iters 4 --log-interval 2
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from rocm_apex_tpu.amp import all_finite
from rocm_apex_tpu.models import BertConfig, BertModel
from rocm_apex_tpu.optimizers import fused_lamb
from rocm_apex_tpu.transformer.testing import parse_args
from rocm_apex_tpu.utils.tree import path_str


def main():
    args = parse_args(
        defaults=dict(
            num_layers=4, hidden_size=256, num_attention_heads=8,
            seq_length=128, max_position_embeddings=128,
            micro_batch_size=8, train_iters=20, lr=1e-3, log_interval=5,
            weight_decay=0.01,
        ),
        ignore_unknown_args=True,
    )
    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    dp = len(devices)

    cfg = BertConfig(
        vocab_size=8192,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_attention_heads=args.num_attention_heads,
        max_position_embeddings=args.max_position_embeddings,
        ffn_hidden_size=args.ffn_hidden_size,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_parallel_size=1,
        add_binary_head=False,
    )
    model = BertModel(cfg)
    b_local, seq = args.micro_batch_size, args.seq_length
    MASK_ID = 1

    tokens0 = jnp.ones((b_local, seq), jnp.int32)
    params = model.init(jax.random.PRNGKey(args.seed), tokens0)

    # LAMB no-decay mask for biases and norm params (the standard BERT
    # recipe; reference FusedLAMB exclude_from_weight_decay usage)
    decay_mask = jax.tree_util.tree_map_with_path(
        lambda path, leaf: not (
            leaf.ndim <= 1
            or "layernorm" in path_str(path).lower()
            or "bias" in path_str(path).lower()
        ),
        params,
    )
    opt = fused_lamb(
        args.lr, weight_decay=args.weight_decay, weight_decay_mask=decay_mask
    )
    ostate = opt.init(params)

    def local_step(params, ostate, tokens, labels, mlm_mask):
        def loss_fn(p):
            losses, _ = model.apply(
                p, tokens, jnp.ones_like(tokens), lm_labels=labels
            )
            return jnp.sum(losses * mlm_mask) / jnp.maximum(
                jnp.sum(mlm_mask), 1.0
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.lax.pmean(grads, "data")
        u, ostate2 = opt.update(grads, ostate, params)
        return (
            optax.apply_updates(params, u),
            ostate2,
            jax.lax.pmean(loss, "data"),
        )

    step = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data"), P("data")),
            out_specs=(P(), P(), P()),
            check_rep=False,
        )
    )

    rng = jax.random.PRNGKey(args.seed + 1)
    t0 = time.perf_counter()
    for it in range(args.train_iters):
        rng, k1, k2 = jax.random.split(rng, 3)
        labels = jax.random.randint(
            k1, (b_local * dp, seq), 2, cfg.vocab_size
        )
        mlm = jax.random.bernoulli(k2, 0.15, (b_local * dp, seq))
        tokens = jnp.where(mlm, MASK_ID, labels)
        params, ostate, loss = step(
            params, ostate, tokens, labels, mlm.astype(jnp.float32)
        )
        if (it + 1) % args.log_interval == 0:
            lv = float(loss)
            dt = (time.perf_counter() - t0) / args.log_interval
            print(
                f"iter {it + 1}: mlm loss {lv:.4f}  "
                f"{b_local * dp * seq / dt:.0f} tokens/s"
            )
            t0 = time.perf_counter()


if __name__ == "__main__":
    main()
