"""Megatron-style GPT pretraining: TP x DP over the device mesh.

The analogue of the reference's transformer bring-up scripts
(reference: tests/L0/run_transformer/run_megatron_gpt_pipeline.py +
apex/transformer/testing/standalone_gpt.py driven by the Megatron
argument system). One process drives the whole mesh: tensor-parallel
layers shard over the ``tensor`` axis inside `shard_map`, gradients
psum over ``data``, the mixed-precision Adam state (bf16 model + fp32
masters) updates under dynamic loss scaling with model-parallel-aware
found_inf sync.

CPU smoke (2-way TP x 4-way DP):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/gpt_train.py --tensor-model-parallel-size 2 \
        --num-layers 2 --hidden-size 64 --num-attention-heads 4 \
        --seq-length 32 --micro-batch-size 2 --train-iters 4
"""

import hashlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import optax

from rocm_apex_tpu.amp import all_finite
from rocm_apex_tpu.checkpoint import CheckpointManager
from rocm_apex_tpu.contrib.optimizers import distributed_fused_adam
from rocm_apex_tpu.models.gpt import GPTConfig, GPTModel, gpt_loss_fn
from rocm_apex_tpu.monitor import (
    SLO,
    BurnRule,
    FlightRecorder,
    JsonlWriter,
    MetricRegistry,
    Metrics,
    MetricsLogger,
    RegistryWriter,
    SLOMonitor,
    Tracer,
    group_nonfinite,
    model_flops,
    start_exporter,
    tree_norm,
)
from rocm_apex_tpu.optimizers.mixed import MixedPrecisionAdam
from rocm_apex_tpu.optimizers.packed import PackedOptimizerStep
from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.transformer.amp import GradScaler
from rocm_apex_tpu.transformer.testing import parse_args


def _observability_args(parser):
    g = parser.add_argument_group(title="observability")
    g.add_argument(
        "--trace", type=str, default=None, metavar="PATH",
        help="export a Chrome trace-event JSON of the run's step "
             "spans (monitor.Tracer; load in Perfetto)",
    )
    g.add_argument(
        "--flight-recorder", type=str, default=None, metavar="PATH",
        const="nan_dump.jsonl", nargs="?",
        help="arm the numerics flight recorder: per-param-group "
             "nonfinite probes ride the step metrics and a NaN/Inf "
             "anomaly dumps a jsonl bundle to PATH "
             "(monitor.FlightRecorder)",
    )
    g.add_argument(
        "--metrics-port", type=int, default=None, metavar="PORT",
        help="serve /metrics (Prometheus text), /healthz, /varz on "
             "127.0.0.1:PORT for the run's telemetry registry "
             "(monitor.RegistryWriter mirror of every flushed "
             "scalar); 0 = ephemeral, the bound port prints on the "
             "'metrics:' line",
    )
    g.add_argument(
        "--slo", type=float, default=None, const=-1.0, nargs="?",
        metavar="MS",
        help="arm a step-time SLO (objective: 90%% of steps finish "
             "within MS milliseconds) with Google-SRE multi-window "
             "burn-rate alerting (monitor.SLOMonitor); omit MS to "
             "auto-set the threshold to 3x the first logging "
             "window's mean step time. Alerts print at the end and "
             "ride /varz when --metrics-port is set",
    )
    g2 = parser.add_argument_group(title="distributed optimizer")
    g2.add_argument(
        "--dist-opt", action="store_true",
        help="shard the Adam state over the data-parallel axis "
             "(contrib.optimizers.distributed_fused_adam: "
             "reduce-scatter grads -> 1/dp-sharded update -> "
             "allgather params, the reference DistributedFusedAdam "
             "semantics); composes with the dynamic loss scaler — the "
             "unscale + found_inf probe runs fused on the packed grad "
             "buffers before the reduce-scatter, and the scaler's "
             "halve/grow logic reads the optimizer-reported flag",
    )
    g2.add_argument(
        "--comm-dtype", default="fp32", choices=("fp32", "int8"),
        help="wire dtype for the ring collectives: int8 quantizes each "
             "hop with per-row fp32 scale sidecars "
             "(ops/quantized_collectives.py) — under --dist-opt the "
             "ZeRO grad reduce-scatter and param all-gather, under "
             "--collective-matmul the TP-boundary rings; fp32 keeps "
             "the plain full-precision collectives",
    )
    g3 = parser.add_argument_group(title="checkpointing (examples)")
    g3.add_argument(
        "--checkpoint-dir", type=str, default=None, metavar="DIR",
        help="enable stepped checkpoints + autoresume "
             "(checkpoint.CheckpointManager): restore the latest step "
             "in DIR if one exists, save every --save-interval iters "
             "(final iter always), and save-and-exit cleanly on "
             "SIGTERM. The saved tree is the FULL training state — "
             "fp32 masters / Adam moments (incl. the ZeRO shards and "
             "their implicit int8-comm error-feedback residuals under "
             "--dist-opt --comm-dtype int8) and the loss-scaler "
             "counters — so a killed run resumes bitwise",
    )
    g2.add_argument(
        "--packed-update", action="store_true",
        help="run the optimizer step over packed dtype-group buffers "
             "(optimizers.PackedOptimizerStep): one-pass unscale + "
             "found_inf + Adam update per dtype buffer, O(dtype-groups) "
             "traced equations instead of O(leaves); ignored under "
             "--dist-opt (the ZeRO path is always packed)",
    )
    return parser


def main():
    args = parse_args(
        extra_args_provider=_observability_args,
        defaults=dict(
            num_layers=4, hidden_size=256, num_attention_heads=8,
            seq_length=256, max_position_embeddings=256,
            micro_batch_size=4, train_iters=20, lr=1e-4, log_interval=5,
        ),
        ignore_unknown_args=True,
    )
    tp = args.tensor_model_parallel_size
    mesh = parallel_state.initialize_model_parallel(tp, 1)
    dp = parallel_state.get_data_parallel_world_size()
    print(f"mesh: data={dp} x tensor={tp}")

    cfg = GPTConfig(
        vocab_size=8192,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_attention_heads=args.num_attention_heads,
        max_position_embeddings=args.max_position_embeddings,
        ffn_hidden_size=args.ffn_hidden_size,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_parallel_size=tp,
        init_method_std=args.init_method_std,
        # the argument system migrates --checkpoint-activations to
        # activations_checkpoint_method='uniform' (reference semantics)
        checkpoint_activations=args.activations_checkpoint_method
        is not None,
        # --sequence-parallel shards the inter-boundary activations
        # over the tensor axis; --collective-matmul rides only if the
        # reference's async-allreduce opt-out was not given
        sequence_parallel=args.sequence_parallel,
        collective_matmul=(
            args.collective_matmul
            and args.async_tensor_model_parallel_allreduce
        ),
        comm_dtype=(
            args.comm_dtype if args.collective_matmul else "fp32"
        ),
    )
    model = GPTModel(cfg)
    if args.packed_update and not args.dist_opt:
        opt = PackedOptimizerStep(
            "adam", args.lr, weight_decay=args.weight_decay
        )
    else:
        opt = MixedPrecisionAdam(args.lr, weight_decay=args.weight_decay)
    scaler = GradScaler(axis_names=(parallel_state.TENSOR_AXIS,))
    dist = (
        distributed_fused_adam(
            args.lr, weight_decay=args.weight_decay,
            axis_name=parallel_state.DATA_AXIS,
            # found_inf must agree across TP ranks too: the probe sees
            # only this rank's grad shards
            probe_sync_axes=(parallel_state.TENSOR_AXIS,),
            comm_dtype=args.comm_dtype,
        )
        if args.dist_opt else None
    )

    b_local = args.micro_batch_size
    seq = args.seq_length

    def local_init(tokens):
        params32 = model.init(jax.random.PRNGKey(args.seed), tokens)
        if dist is not None:
            # ZeRO path: fp32 params beside 1/dp Adam shards; the
            # scaler state stays in the carry only so both paths share
            # one step/init signature
            return (params32, dist.init(params32)), scaler.init()
        return opt.init(params32), scaler.init()

    def local_step_dist(state, sstate, tokens, labels):
        params, ostate = state

        def loss_fn(p):
            losses = model.apply(p, tokens, labels=labels)
            return gpt_loss_fn(losses) * scaler.loss_scale(sstate)

        scaled, grads = jax.value_and_grad(loss_fn)(params)
        inv_scale = 1.0 / scaler.loss_scale(sstate)
        # NO grad pmean here: the optimizer's reduce-scatter over the
        # data axis IS the gradient averaging — that is the ZeRO
        # bargain (all-reduce bytes, but the Adam state the result
        # feeds lives 1/dp-sharded). The scaler composes through the
        # optimizer: the inv_scale multiply + found_inf probe run as
        # one fused pass over the PACKED grad buffers before the
        # reduce-scatter (synced over data + tensor axes), and on
        # overflow the kernel freezes masters/moments in place
        updates, ostate2, info = dist.update(
            grads, ostate, params, inv_scale=inv_scale, with_info=True
        )
        params2 = optax.apply_updates(params, updates)
        # host-visible scale bookkeeping (halve/grow/skip counters)
        # unchanged from the non-dist path — the optimizer already
        # applied the skip, so the returned flag only drives the scale
        sstate2, _ = scaler.update(sstate, info["found_inf"])
        loss = scaled * inv_scale
        unscaled = jax.tree_util.tree_map(lambda g: g * inv_scale, grads)
        metrics = (
            Metrics.empty()
            .record("loss", loss)
            .record_norm("grad_norm", unscaled)
            .record_ratio_norms(unscaled, params, prefix="grad_ratio")
            .record("loss_scale", sstate2.loss_scale)
            .record("overflows", sstate2.overflows)
        )
        if args.flight_recorder is not None:
            metrics = metrics.merge(Metrics(group_nonfinite(
                grads, axis_name=parallel_state.TENSOR_AXIS
            )))
        # pre-reduce-scatter grads differ across dp ranks, so every
        # scalar above is rank-local — mean them so the P() out_spec
        # (check_rep=False) carries honest replicated values
        metrics = jax.tree_util.tree_map(
            lambda x: jax.lax.pmean(x, parallel_state.DATA_AXIS),
            metrics,
        )
        return (params2, ostate2), sstate2, metrics

    def local_step(state, sstate, tokens, labels):
        def loss_fn(p):
            losses = model.apply(p, tokens, labels=labels)
            return gpt_loss_fn(losses) * scaler.loss_scale(sstate)

        scaled, grads = jax.value_and_grad(loss_fn)(state.model)
        grads = jax.lax.pmean(grads, parallel_state.DATA_AXIS)
        found_inf = ~all_finite(grads)
        sstate2, skip = scaler.update(sstate, found_inf)
        state2 = opt.step(
            state, grads,
            grad_scale=1.0 / scaler.loss_scale(sstate), skip=skip,
        )
        inv_scale = 1.0 / scaler.loss_scale(sstate)
        loss = scaled * inv_scale
        # in-graph telemetry (monitor.Metrics): one pytree of fp32
        # scalars riding the step outputs — the UNSCALED grad norm
        # (grads here still carry the loss scale) over the rank-LOCAL
        # trees (TP shards; identical across dp ranks after the pmean —
        # a spike diagnostic rather than an exact global norm), plus
        # the scaler's own observability counters
        unscaled = jax.tree_util.tree_map(lambda g: g * inv_scale, grads)
        # packed states keep masters as flat buffers — the bf16 model
        # tree is the per-leaf ratio-norm denominator there
        denom = state.model if args.packed_update else state.master
        metrics = (
            Metrics.empty()
            .record("loss", loss)
            .record_norm("grad_norm", unscaled)
            .record_ratio_norms(unscaled, denom, prefix="grad_ratio")
            .record("loss_scale", sstate2.loss_scale)
            .record("overflows", sstate2.overflows)
        )
        if args.flight_recorder is not None:
            # per-group nonfinite probes for the flight recorder —
            # shard-partial grads psum over the tensor axis per the
            # Metrics convention. Gated: the default program carries
            # ZERO extra equations (the recorder-off acceptance bar).
            metrics = metrics.merge(Metrics(group_nonfinite(
                grads, axis_name=parallel_state.TENSOR_AXIS
            )))
        return state2, sstate2, metrics

    data_spec = P(parallel_state.DATA_AXIS)
    init_f = jax.jit(
        shard_map(
            local_init, mesh=mesh,
            in_specs=(data_spec,), out_specs=(P(), P()),
            check_rep=False,
        )
    )
    # the (state, sstate) carry is donated: the loop reassigns both
    # every iteration and the checkpoint gather only reads the current
    # step's output, so the old buffers are dead the moment step_f
    # returns. Halves peak optimizer-state memory; the donation is a
    # standing contract pinned by `tools/graphlint.py` (gpt_train_bf16).
    step_f = jax.jit(
        shard_map(
            local_step_dist if dist is not None else local_step,
            mesh=mesh,
            in_specs=(P(), P(), data_spec, data_spec),
            out_specs=(P(), P(), P()),
            check_rep=False,
        ),
        donate_argnums=(0, 1),
    )

    # per-iteration data keys FOLD IN the iteration index instead of
    # chaining splits, so a resumed run regenerates iteration N's batch
    # bitwise without replaying iterations 0..N-1
    base_rng = jax.random.PRNGKey(args.seed + 1)
    tokens0 = jnp.ones((b_local * dp, seq), jnp.int32)
    state, sstate = init_f(tokens0)

    # --- checkpointing (--checkpoint-dir): rank-stacked host view ----
    # Training state lives at per-rank local shapes behind the P()
    # out_specs (check_rep=False) — the "replicated" claim is false for
    # TP param shards and 1/dp ZeRO shards, so saving the host view of
    # `state` directly would persist rank 0's shard for every rank. The
    # gather jit all-gathers over BOTH mesh axes into a genuinely
    # replicated (tp, dp, ...) stack per leaf; the scatter jit is its
    # bitwise inverse (pure data movement, no arithmetic). Fine at
    # example scale — a production run would hand orbax the sharded
    # arrays directly.
    def local_gather(state, sstate):
        tree = jax.lax.all_gather(
            (state, sstate), parallel_state.DATA_AXIS
        )
        return jax.lax.all_gather(tree, parallel_state.TENSOR_AXIS)

    def local_scatter(tree):
        ti = jax.lax.axis_index(parallel_state.TENSOR_AXIS)
        di = jax.lax.axis_index(parallel_state.DATA_AXIS)
        return jax.tree_util.tree_map(lambda x: x[ti, di], tree)

    mgr = None
    start_it = 0
    if args.checkpoint_dir is not None:
        gather_f = jax.jit(shard_map(
            local_gather, mesh=mesh,
            in_specs=(P(), P()), out_specs=P(), check_rep=False,
        ))
        scatter_f = jax.jit(shard_map(
            local_scatter, mesh=mesh,
            in_specs=(P(),), out_specs=(P(), P()), check_rep=False,
        ))
        # SIGTERM → should_exit(): the loop saves and leaves cleanly
        mgr = CheckpointManager(args.checkpoint_dir)
        latest = mgr.latest_step()
        if latest is not None:
            restored = mgr.restore(
                latest, template=jax.device_get(gather_f(state, sstate))
            )
            state, sstate = scatter_f(restored)
            start_it = latest
            print(
                f"resumed from {args.checkpoint_dir} at iter {latest}",
                file=sys.stderr,
            )
    if dist is not None:
        # sharded leaves exit shard_map at their LOCAL (1/dp) shapes
        # under the P() out_spec, so summing bytes here reads the
        # per-chip optimizer footprint directly
        opt_bytes = sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(state[1])
        )
        print(
            f"ZeRO optimizer state: {opt_bytes / 2**20:.2f} MiB/chip "
            f"(dp={dp})"
        )

    # host-side pipeline (monitor.MetricsLogger): jsonl metric lines on
    # stdout every log_interval steps — window means of the in-graph
    # Metrics plus step time (Timers sync semantics: end_step fetches
    # the loss), tokens/sec, and MFU from the shared model_flops
    # accounting. Param count via eval_shape of an unsharded replica
    # (abstract — no compute; local leaves are 1/tp shards under TP).
    import dataclasses

    cfg_count = dataclasses.replace(
        cfg, tensor_parallel_size=1, sequence_parallel=False,
        collective_matmul=False,
    )
    raw_count = sum(
        int(np.prod(x.shape))
        for x in jax.tree_util.tree_leaves(
            jax.eval_shape(
                lambda t: GPTModel(cfg_count).init(
                    jax.random.PRNGKey(0), t
                ),
                tokens0[:1],
            )
        )
    )
    logger = MetricsLogger(
        writers=[JsonlWriter(stream=sys.stdout)],
        window=args.log_interval,
        tokens_per_step=b_local * dp * seq,
        flops_per_step=model_flops(
            cfg, b_local * dp, seq, raw_param_count=raw_count
        ),
        n_chips=tp * dp,
    )
    # span tracer (--trace): one host span per train step, aligned
    # with any live device capture via StepTraceAnnotation; exported
    # as Perfetto-loadable Chrome trace JSON at the end of the run
    tracer = Tracer(enabled=args.trace is not None)
    # telemetry plane (--metrics-port / --slo): a RegistryWriter
    # mirrors every flushed scalar into a MetricRegistry so the
    # training run exports through the SAME /metrics + SLO surface as
    # the serving engine (docs/observability.md "Telemetry & SLOs")
    registry = None
    slo_monitor = None
    exporter = None
    if args.metrics_port is not None or args.slo is not None:
        registry = MetricRegistry()
        logger.writers.append(RegistryWriter(registry))
        if args.slo is not None:
            slo_monitor = SLOMonitor(registry=registry, tracer=tracer)
        if args.metrics_port is not None:
            exporter = start_exporter(
                registry, port=args.metrics_port,
                slo_monitor=slo_monitor,
            )
            print(f"metrics: {exporter.url}", flush=True)
    # numerics flight recorder (--flight-recorder): the last-k metric
    # snapshots ride a host ring; a NaN/Inf anomaly dumps a jsonl
    # bundle naming the offending param group
    recorder = (
        FlightRecorder(path=args.flight_recorder)
        if args.flight_recorder is not None else None
    )
    # context-managed logger: the trailing partial window (short runs'
    # last < log_interval steps) flushes on exit
    with logger:
        for it in range(start_it, args.train_iters):
            k = jax.random.fold_in(base_rng, it)
            tokens = jax.random.randint(
                k, (b_local * dp, seq), 0, cfg.vocab_size
            )
            labels = jnp.roll(tokens, -1, axis=1)
            logger.start_step()
            with tracer.step_span(it + 1):
                state, sstate, metrics = step_f(
                    state, sstate, tokens, labels
                )
                logger.end_step(sync_on=metrics["loss"])  # fetch = sync
            record = logger.log_step(it + 1, metrics)
            if record is not None and slo_monitor is not None:
                if not slo_monitor.slos:
                    # threshold: the flag's value, or 3x the first
                    # window's mean step time (post-compile steady
                    # state; the compile-heavy first window itself
                    # never enters the histogram ring twice)
                    thresh = (
                        args.slo if args.slo > 0
                        else 3.0 * record["step_time_ms"]
                    )
                    slo_monitor.add(SLO(
                        "train_step_time", 0.9,
                        series=registry.get("train_step_ms"),
                        threshold=thresh,
                        windows=(BurnRule(60.0, 15.0, 2.0),),
                    ))
                slo_monitor.tick()
                slo_monitor.alerts()  # rising edges -> events/tracer
            if recorder is not None:
                bundle = recorder.record(it + 1, metrics)
                if bundle is not None:
                    print(
                        f"iter {it + 1}: NUMERICS ANOMALY in "
                        f"{bundle['offending']} -> "
                        f"{args.flight_recorder}",
                        file=sys.stderr,
                    )
            if record is not None:
                print(
                    f"iter {it + 1}: lm loss {record['loss']:.4f}  "
                    f"{record['tokens_per_sec']:.0f} tokens/s  "
                    f"grad_norm {record['grad_norm']:.3f}  "
                    f"scale {record['loss_scale']:.0f}",
                    file=sys.stderr,
                )
            if mgr is not None:
                if mgr.should_exit():
                    # preemption notice: persist and leave with code 0
                    # — the relaunch resumes at this exact step
                    mgr.save(it + 1, gather_f(state, sstate), force=True)
                    print(
                        f"preemption notice at iter {it + 1}: "
                        f"checkpoint saved, exiting cleanly",
                        file=sys.stderr,
                    )
                    break
                if (
                    args.save_interval
                    and (it + 1) % args.save_interval == 0
                    and (it + 1) < args.train_iters
                ):
                    mgr.save(it + 1, gather_f(state, sstate))
    if mgr is not None:
        if mgr.latest_step() != args.train_iters and not mgr.should_exit():
            mgr.save(
                args.train_iters, gather_f(state, sstate), force=True
            )
        # full-state digest: kill-and-resume is bitwise iff this line
        # matches the uninterrupted run's (masters, moments — incl.
        # ZeRO shards and int8-comm residual state — and the scaler
        # counters all hash in)
        h = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(
            jax.device_get(gather_f(state, sstate))
        ):
            h.update(np.ascontiguousarray(leaf).tobytes())
        print(f"state digest: {h.hexdigest()}")
        mgr.wait_until_finished()
        mgr.close()
    if slo_monitor is not None:
        fired = slo_monitor.events
        print(
            f"slo: {len(fired)} burn-rate alert(s)"
            + (
                " — " + "; ".join(
                    f"{e['slo']} burn={e['burn_long']:.1f}x "
                    f"(factor {e['factor']:.1f})" for e in fired
                ) if fired else ""
            ),
            file=sys.stderr,
        )
    if exporter is not None:
        exporter.close()
    if args.trace is not None:
        n = tracer.export_chrome_trace(args.trace)
        print(f"wrote {n} trace events to {args.trace}", file=sys.stderr)


if __name__ == "__main__":
    main()
