"""ImageNet-style ResNet training under amp + data parallelism.

TPU-native rebuild of the reference's flagship example
(reference: examples/imagenet/main_amp.py — argparse flags at :44,
amp.initialize + apex DDP wrap + speed meter). One process drives all
local devices through a `shard_map` over the ``data`` mesh axis; the
reference's `torch.distributed.launch` + NCCL DDP become the mesh +
gradient psum. Synthetic data by default (this repo carries no
ImageNet); ``--data-dir`` drives the REAL input pipeline
(rocm_apex_tpu.data: ImageFolder scan, worker-thread decode, native
fast_collate, prefetch + async device_put with on-device
normalization — the reference's DataLoader + data_prefetcher).

Run (single host, all devices):
    python examples/imagenet_train.py --arch resnet50 --opt-level O5 \
        --batch-size 128 --steps 100 [--data-dir /data/imagenet/train]
CPU smoke:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/imagenet_train.py --arch resnet18 --steps 2 \
        --batch-size 16 --image-size 32
"""

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

from rocm_apex_tpu import amp, models
from rocm_apex_tpu.optimizers import FusedSGD
from rocm_apex_tpu.parallel import sync_gradients


def parse_args():
    p = argparse.ArgumentParser(description="rocm_apex_tpu imagenet example")
    p.add_argument("--arch", default="resnet50",
                   choices=["resnet_tiny", "resnet18", "resnet34",
                            "resnet50", "resnet101"])
    p.add_argument("--opt-level", default="O5",
                   choices=["O0", "O1", "O2", "O3", "O4", "O5"])
    p.add_argument("--loss-scale", default=None,
                   help="static scale or 'dynamic' (default: per opt level)")
    p.add_argument("--keep-batchnorm-fp32", default=None, type=str)
    p.add_argument("--sync-bn", action="store_true")
    p.add_argument("--batch-size", type=int, default=128, help="global batch")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument(
        "--data-dir", default=None,
        help="ImageFolder root (class dirs of jpg/png/npy). Default: "
        "synthetic data (this repo carries no ImageNet).",
    )
    p.add_argument(
        "--loader-workers", type=int, default=4,
        help="decode threads for --data-dir (the reference's "
        "DataLoader num_workers; JPEG decode scales with host cores)",
    )
    return p.parse_args()


def build_training(
    arch="resnet50",
    opt_level="O5",
    *,
    batch_size,
    image_size,
    num_classes=1000,
    loss_scale=None,
    keep_batchnorm_fp32=None,
    sync_bn=False,
    lr=0.1,
    momentum=0.9,
    weight_decay=1e-4,
    seed=0,
    verbosity=1,
):
    """The example's training setup, importable: returns
    ``(step, state)`` where ``step(*state, x, y) -> (*state, loss)`` is
    the jitted shard_map train step over the ``data`` mesh axis and
    ``state = (params, batch_stats, opt_state, scaler_state)``.

    tests/L1/test_determinism_imagenet.py drives the determinism
    cross-product through THIS function — the real example step, mesh
    included — mirroring how the reference's L1 harness executes
    main_amp.py itself (reference: tests/L1/common/run_test.sh:20-27).
    """
    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    dp = len(devices)
    if batch_size % dp:
        raise ValueError(f"batch size {batch_size} not divisible by {dp}")

    model = getattr(models, arch)(
        num_classes=num_classes,
        sync_bn_axis="data" if sync_bn else None,
    )

    x0 = jnp.zeros((batch_size // dp, image_size, image_size, 3))
    variables = model.init(jax.random.PRNGKey(seed), x0)
    params, batch_stats = variables["params"], variables.get("batch_stats", {})

    overrides = {}
    if loss_scale is not None:
        overrides["loss_scale"] = loss_scale
    if keep_batchnorm_fp32 is not None:
        overrides["keep_batchnorm_fp32"] = keep_batchnorm_fp32
    optimizer = FusedSGD(lr, momentum=momentum, weight_decay=weight_decay)
    params, optimizer, amp_state = amp.initialize(
        params, optimizer, opt_level=opt_level, verbosity=verbosity,
        **overrides
    )
    opt_state = optimizer.init(params)
    scaler_state = amp_state.scaler_states

    def local_step(params, batch_stats, opt_state, scaler_states, x, y):
        st = amp_state.replace(scaler_states=scaler_states)

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats},
                x,
                mutable=["batch_stats"],
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y
            ).mean()
            return amp.scale_loss(ce, st), (mut["batch_stats"], ce)

        (_, (new_bs, ce)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        grads = sync_gradients(grads, "data")
        grads, found_inf = amp.unscale_grads(grads, st)
        st2, skip = amp.update_scale(st, found_inf)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params = amp.skip_step(skip, new_params, params)
        new_opt = amp.skip_step(skip, new_opt, opt_state)
        return new_params, new_bs, new_opt, st2.scaler_states, ce

    step = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P(), P(), P()),
        check_rep=False,
    )
    return jax.jit(step), (params, batch_stats, opt_state, scaler_state)


def main():
    args = parse_args()

    loss_scale = None
    if args.loss_scale is not None:
        loss_scale = (
            "dynamic" if args.loss_scale == "dynamic" else float(args.loss_scale)
        )
    keep_bn = None
    if args.keep_batchnorm_fp32 is not None:
        keep_bn = args.keep_batchnorm_fp32 == "True"
    step, (params, batch_stats, opt_state, scaler_state) = build_training(
        args.arch,
        args.opt_level,
        batch_size=args.batch_size,
        image_size=args.image_size,
        num_classes=args.num_classes,
        loss_scale=loss_scale,
        keep_batchnorm_fp32=keep_bn,
        sync_bn=args.sync_bn,
        lr=args.lr,
        momentum=args.momentum,
        weight_decay=args.weight_decay,
    )

    def batches(rng):
        """Synthetic stand-in for the DataLoader + fast_collate pipeline
        (reference: main_amp.py data_prefetcher)."""
        while True:
            rng, k1, k2 = jax.random.split(rng, 3)
            x = jax.random.normal(
                k1,
                (args.batch_size, args.image_size, args.image_size, 3),
                jnp.float32,
            )
            y = jax.random.randint(k2, (args.batch_size,), 0, args.num_classes)
            yield x, y

    if args.data_dir:
        # the real input pipeline: ImageFolder scan, worker-thread
        # decode, native fast_collate, prefetch + async device_put
        # (rocm_apex_tpu/data — the reference's DataLoader +
        # data_prefetcher machinery)
        from rocm_apex_tpu.data import ImageFolder, PrefetchLoader

        it = iter(
            PrefetchLoader(
                ImageFolder(args.data_dir),
                batch_size=args.batch_size,
                image_size=args.image_size,
                rng=np.random.RandomState(1),
                num_workers=args.loader_workers,
                # bound the producer to the loop: without it the
                # loader thread outlives the break at args.steps
                steps=args.steps,
            )
        )
    else:
        it = batches(jax.random.PRNGKey(1))
    t0 = time.perf_counter()
    for i, (x, y) in enumerate(it):
        if i >= args.steps:
            break
        params, batch_stats, opt_state, scaler_state, ce = step(
            params, batch_stats, opt_state, scaler_state, x, y
        )
        if (i + 1) % args.print_freq == 0:
            loss = float(ce)  # value fetch = device sync
            dt = (time.perf_counter() - t0) / args.print_freq
            print(
                f"step {i + 1}: loss {loss:.4f}  "
                f"{args.batch_size / dt:.1f} img/s  "
                f"scale {float(scaler_state[0].loss_scale):.0f}"
            )
            t0 = time.perf_counter()


if __name__ == "__main__":
    main()
