"""KV-cached GPT generation through the continuous-batching engine.

The serving-side counterpart of `examples/gpt_train.py`: builds a GPT,
leases cache slots to a queue of mixed-length requests, and drives the
engine's admit → decode → evict loop, printing per-request outputs and
aggregate decode throughput. With random init the tokens are noise —
the point is the serving machinery: one compiled prefill, ONE compiled
decode step reused across every tick (the trace counters printed at
the end must both read 1), per-slot KV cache reuse.

CPU smoke:
    JAX_PLATFORMS=cpu python examples/generate_gpt.py \
        --num-layers 2 --hidden-size 64 --num-attention-heads 4 \
        --max-seq-len 64 --num-slots 2 --num-requests 6 \
        --max-new-tokens 8
"""

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from rocm_apex_tpu.inference import InferenceEngine, SamplingParams
from rocm_apex_tpu.models.gpt import GPTConfig, GPTModel


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--hidden-size", type=int, default=64)
    p.add_argument("--num-attention-heads", type=int, default=4)
    p.add_argument("--vocab-size", type=int, default=512)
    p.add_argument("--max-seq-len", type=int, default=64,
                   help="cache capacity == max_position_embeddings")
    p.add_argument("--max-prompt-len", type=int, default=16)
    p.add_argument("--num-slots", type=int, default=2)
    p.add_argument("--num-requests", type=int, default=6)
    p.add_argument("--max-new-tokens", type=int, default=8)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    cfg = GPTConfig(
        vocab_size=args.vocab_size,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_attention_heads=args.num_attention_heads,
        max_position_embeddings=args.max_seq_len,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_parallel_size=1,
    )
    model = GPTModel(cfg)
    params = model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, args.max_prompt_len), jnp.int32),
    )
    n_params = sum(
        x.size for x in jax.tree_util.tree_leaves(params)
    )
    print(f"model: {n_params / 1e6:.1f}M params, "
          f"{jax.default_backend()} backend")

    eng = InferenceEngine(
        model, params,
        num_slots=args.num_slots,
        max_prompt_len=args.max_prompt_len,
        capacity=args.max_seq_len,
        sampling=SamplingParams(
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
        ),
        seed=args.seed,
    )

    rng = np.random.RandomState(args.seed)
    prompts = [
        rng.randint(0, args.vocab_size,
                    size=rng.randint(1, args.max_prompt_len + 1)).tolist()
        for _ in range(args.num_requests)
    ]

    t0 = time.perf_counter()
    results = eng.generate(prompts, max_new_tokens=args.max_new_tokens)
    dt = time.perf_counter() - t0

    n_gen = sum(len(r.tokens) for r in results)
    for r in results:
        print(f"req {r.request_id}: prompt[{len(r.prompt)}] -> "
              f"{r.tokens} ({r.finish_reason})")
    print(f"generated {n_gen} tokens across {len(results)} requests "
          f"in {dt:.2f}s ({n_gen / dt:.1f} tok/s) | "
          f"prefill traces={eng.prefill_trace_count} "
          f"decode traces={eng.decode_trace_count}")
    if eng.decode_trace_count != 1 or eng.prefill_trace_count != 1:
        raise SystemExit("decode/prefill retraced — serving loop broken")


if __name__ == "__main__":
    main()
