"""KV-cached GPT generation through the continuous-batching engine.

The serving-side counterpart of `examples/gpt_train.py`: builds a GPT,
leases cache slots to a queue of mixed-length requests, and drives the
engine's admit → prefill-chunk → decode → evict loop, printing
per-request outputs and aggregate serving throughput. With random init
the tokens are noise — the point is the serving machinery: the
token-budget chunked-prefill scheduler packs pending prompt tokens
into ONE compiled mixed chunk+decode step per tick (the trace counters
printed at the end must stay at 1), prompts longer than any pad width
stream through in budget-sized pieces, and decodes never stall behind
a prefill. ``--token-budget 0`` selects the legacy whole-prompt
prefill (the A/B baseline, pad width ``--max-prompt-len``).

CPU smoke:
    JAX_PLATFORMS=cpu python examples/generate_gpt.py \
        --num-layers 2 --hidden-size 64 --num-attention-heads 4 \
        --max-seq-len 64 --num-slots 2 --num-requests 6 \
        --max-new-tokens 8 --token-budget 6
"""

import argparse
import signal
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from rocm_apex_tpu.inference import InferenceEngine, SamplingParams
from rocm_apex_tpu.models.gpt import GPTConfig, GPTModel
from rocm_apex_tpu.monitor import JsonlWriter, Tracer


def _install_sigterm_drain() -> threading.Event:
    """SIGTERM → graceful drain instead of a mid-tick kill.

    Same shape as CheckpointManager's preemption hook: flip an Event
    from the (async-signal-safe) handler and let the serving loop act
    on it at the next tick boundary; chain any previously installed
    handler so we compose with outer supervisors.
    """
    stop = threading.Event()
    if threading.current_thread() is not threading.main_thread():
        return stop  # signal.signal is main-thread-only
    prev = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        stop.set()
        if callable(prev):
            prev(signum, frame)

    try:
        signal.signal(signal.SIGTERM, _handler)
    except (ValueError, OSError):
        pass
    return stop


def main():
    stop = _install_sigterm_drain()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--hidden-size", type=int, default=64)
    p.add_argument("--num-attention-heads", type=int, default=4)
    p.add_argument("--vocab-size", type=int, default=512)
    p.add_argument("--max-seq-len", type=int, default=64,
                   help="cache capacity == max_position_embeddings")
    p.add_argument("--max-prompt-len", type=int, default=16,
                   help="prompt-length cap for the RANDOM workload "
                        "below; also the pad width of the legacy "
                        "whole-prompt path (--token-budget 0)")
    p.add_argument("--token-budget", type=int, default=16,
                   help="prefill tokens absorbed per engine tick "
                        "(chunked-prefill scheduler); 0 = legacy "
                        "whole-prompt prefill")
    p.add_argument("--prefill-chunk", type=int, default=None,
                   help="optional cap on tokens taken from ONE "
                        "request per tick (fairness inside the budget)")
    p.add_argument("--num-slots", type=int, default=2)
    p.add_argument("--replicas", type=int, default=1,
                   help="serve through a ReplicaRouter fleet of N "
                        "identical engines (N >= 2): prefix-affinity "
                        "+ least-loaded placement, failover with "
                        "token-identical recovery, rolling drain; "
                        "needs a token budget (migration recomputes "
                        "through chunked prefill); 1 = single engine")
    p.add_argument("--num-requests", type=int, default=6)
    p.add_argument("--max-new-tokens", type=int, default=8)
    p.add_argument("--spec-k", type=int, default=0,
                   help="speculative decoding: up to K tokens per slot "
                        "drafted by the n-gram self-drafter and "
                        "verified in the same mixed step (0 = off; "
                        "requires a token budget >= num_slots*(K+1) "
                        "for full-rate drafting)")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--trace", type=str, default=None, metavar="PATH",
                   help="write a Chrome trace-event JSON of per-request"
                        " serving timelines to PATH (load in Perfetto)"
                        " and per-request completion records to"
                        " PATH.requests.jsonl")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve /metrics (Prometheus text), /healthz "
                        "(engine liveness), /varz (JSON) on "
                        "127.0.0.1:PORT while the loop runs; 0 = "
                        "ephemeral (the bound port is printed on the "
                        "'metrics:' line)")
    args = p.parse_args()

    cfg = GPTConfig(
        vocab_size=args.vocab_size,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_attention_heads=args.num_attention_heads,
        max_position_embeddings=args.max_seq_len,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_parallel_size=1,
    )
    model = GPTModel(cfg)
    params = model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, args.max_prompt_len), jnp.int32),
    )
    n_params = sum(
        x.size for x in jax.tree_util.tree_leaves(params)
    )
    chunked = args.token_budget > 0
    # flush: supervisors watch this banner to know the serving loop
    # (and its SIGTERM drain handler) is up, even through a pipe
    print(f"model: {n_params / 1e6:.1f}M params, "
          f"{jax.default_backend()} backend, "
          f"prefill={'budget %d' % args.token_budget if chunked else 'whole-prompt'}",
          flush=True)

    tracer = Tracer(enabled=args.trace is not None)
    engine_kwargs = dict(
        num_slots=args.num_slots,
        max_prompt_len=args.max_prompt_len,
        capacity=args.max_seq_len,
        sampling=SamplingParams(
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
        ),
        seed=args.seed,
        prefill_token_budget=args.token_budget if chunked else None,
        prefill_chunk=args.prefill_chunk,
        tracer=tracer,
        spec_k=args.spec_k,
    )
    router = None
    if args.replicas >= 2:
        if not chunked:
            raise SystemExit(
                "--replicas needs --token-budget > 0: replica "
                "failover recomputes migrated requests through the "
                "chunked prefill"
            )
        if args.trace is not None or args.spec_k > 0:
            raise SystemExit(
                "--replicas does not compose with --trace/--spec-k "
                "in this example (single-engine instrumentation)"
            )
        from rocm_apex_tpu.inference import ReplicaRouter

        router = ReplicaRouter(
            model, params, replicas=args.replicas,
            engine_kwargs=engine_kwargs,
        )
        serve = router
        print(f"fleet: {args.replicas} replicas behind one router",
              flush=True)
    else:
        serve = eng = InferenceEngine(model, params, **engine_kwargs)

    exporter = None
    if args.metrics_port is not None:
        from rocm_apex_tpu.monitor import start_exporter

        if router is not None:
            # merged-per-scrape registry + fleet /healthz (503 only
            # when no replica is healthy); replica detail on /varz
            exporter = start_exporter(
                router=router, port=args.metrics_port
            )
        else:
            exporter = start_exporter(
                eng.registry, port=args.metrics_port, engine=eng
            )
        # flush: the L1 smoke scrapes this address mid-run
        print(f"metrics: {exporter.url}", flush=True)

    rng = np.random.RandomState(args.seed)
    prompts = [
        rng.randint(0, args.vocab_size,
                    size=rng.randint(1, args.max_prompt_len + 1)).tolist()
        for _ in range(args.num_requests)
    ]

    t0 = time.perf_counter()
    for prompt in prompts:
        serve.add_request(prompt, args.max_new_tokens)
    results = []
    drained = False
    while serve.has_work():
        if stop.is_set():
            # SIGTERM: shed the queue, let in-flight requests finish,
            # exit 0 — never kill a request mid-token
            results.extend(serve.drain(shed_queue=True))
            drained = True
            break
        results.extend(serve.step())
    results.sort(key=lambda r: r.request_id)
    dt = time.perf_counter() - t0

    n_gen = sum(len(r.tokens) for r in results)
    if drained:
        shed = sum(1 for r in results if r.finish_reason == "cancelled")
        print(f"SIGTERM: drained gracefully — "
              f"{len(results) - shed} requests completed, "
              f"{shed} shed from the queue")
    for r in results:
        print(f"req {r.request_id}: prompt[{len(r.prompt)}] -> "
              f"{r.tokens} ({r.finish_reason})")
    s = serve.stats()
    if router is not None:
        hist = router.merged_registry().get("serve_ttft_ms")
        traces = [
            router.replica(i).mixed_trace_count
            for i in range(router.num_replicas)
        ]
        print(f"generated {n_gen} tokens across {len(results)} "
              f"requests in {dt:.2f}s ({n_gen / dt:.1f} tok/s) | "
              f"ttft p50/p95={hist.percentile(50):.0f}/"
              f"{hist.percentile(95):.0f}ms (merged fleet) | "
              f"migrations={s['migrations']:.0f} "
              f"quarantines={s['replica_quarantines']:.0f} | "
              f"traces: mixed={traces} (one per replica)")
    else:
        print(f"generated {n_gen} tokens across {len(results)} requests "
              f"in {dt:.2f}s ({n_gen / dt:.1f} tok/s) | "
              f"ttft p50/p95={s['ttft_ms_p50']:.0f}/{s['ttft_ms_p95']:.0f}ms | "
              f"traces: mixed={eng.mixed_trace_count} "
              f"decode={eng.decode_trace_count} "
              f"prefill={eng.prefill_trace_count}")
    if args.spec_k > 0:
        print(f"speculative: k={args.spec_k} "
              f"drafted={s['tokens_drafted']:.0f} "
              f"accepted={s['tokens_accepted']:.0f} "
              f"(acceptance={s['acceptance_rate']:.2f}) "
              f"rollbacks={s['rollbacks']:.0f}")
    if exporter is not None:
        # completion accounting: the registry counters, the delivered
        # results, and stats() must tell one story (the L1 smoke
        # asserts this line says "consistent")
        reg = (
            router.merged_registry() if router is not None
            else eng.registry
        )
        c_done = reg.get("serve_completions_total").total()
        c_gen = reg.get(
            "serve_tokens_total"
        ).value(phase="generated")
        if router is not None:
            # router-shed requests (drain cancels the global queue)
            # never reached an engine, so they are absent from the
            # per-replica completion counters by design
            n_router_shed = len(results) - int(
                sum(
                    router.replica(i).stats()["evicted"]
                    + router.replica(i).stats()["shed"]
                    for i in range(router.num_replicas)
                )
            ) if drained else 0
            ok_acct = (
                c_done == len(results) - n_router_shed
                and c_gen == n_gen
                and s["completed"] == s["submitted"] == len(results)
            )
        else:
            ok_acct = c_done == len(results) and c_gen == n_gen
            if not drained:
                ok_acct = ok_acct and c_done == s["evicted"] + s["shed"]
        print(f"telemetry: completions={c_done:.0f}/{len(results)} "
              f"generated_tokens={c_gen:.0f}/{n_gen} "
              f"({'consistent' if ok_acct else 'MISMATCH'})",
              flush=True)
        exporter.close()
        if not ok_acct:
            raise SystemExit(
                "telemetry counters disagree with results/stats()"
            )
    if args.trace is not None:
        n = tracer.export_chrome_trace(args.trace)
        req_path = args.trace + ".requests.jsonl"
        with open(req_path, "w") as f:
            w = JsonlWriter(stream=f)
            for rec in eng.completions:
                w.emit(rec)
        print(f"trace: {n} events -> {args.trace}; "
              f"{len(eng.completions)} request records -> {req_path}")
    if drained:
        return  # a drained run may stop before every program traced
    if router is not None:
        # host-only fabric: every replica still compiled ONE mixed
        # program; the router never adds a trace
        ok = all(
            router.replica(i).mixed_trace_count == 1
            and router.replica(i).decode_trace_count <= 1
            for i in range(router.num_replicas)
        )
    elif chunked:
        # the fixed-shape contract: ONE mixed program for the whole
        # run regardless of the prompt mix (+ at most one decode-only
        # fast-path program)
        ok = eng.mixed_trace_count == 1 and eng.decode_trace_count <= 1
    else:
        ok = eng.decode_trace_count == 1 and eng.prefill_trace_count == 1
    if not ok:
        raise SystemExit("serving programs retraced — scheduler broken")


if __name__ == "__main__":
    main()
