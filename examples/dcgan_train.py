"""DCGAN training with SyncBatchNorm + amp.

TPU-native rebuild of the reference's DCGAN example
(reference: examples/dcgan/main_amp.py — two models, two optimizers,
`amp.initialize(num_losses=3)` with a scaler per loss). Generator and
discriminator train data-parallel over the mesh; BatchNorm stats
optionally merge across replicas (--sync-bn), the BASELINE.md config-3
scenario.

CPU smoke:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/dcgan_train.py --steps 2 --batch-size 16
"""

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from rocm_apex_tpu import amp
from rocm_apex_tpu.models import Discriminator, Generator
from rocm_apex_tpu.optimizers import FusedAdam
from rocm_apex_tpu.parallel import sync_gradients


def parse_args():
    p = argparse.ArgumentParser(description="rocm_apex_tpu dcgan example")
    p.add_argument("--opt-level", default="O5",
                   choices=["O0", "O1", "O2", "O3", "O4", "O5"])
    p.add_argument("--sync-bn", action="store_true")
    p.add_argument("--batch-size", type=int, default=64, help="global batch")
    p.add_argument("--nz", type=int, default=100)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--print-freq", type=int, default=10)
    return p.parse_args()


def bce_logits(logits, target):
    return optax.sigmoid_binary_cross_entropy(
        logits.astype(jnp.float32), target
    ).mean()


def main():
    args = parse_args()
    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    dp = len(devices)
    local_b = args.batch_size // dp
    bn_axis = "data" if args.sync_bn else None

    netG = Generator(nz=args.nz, sync_bn_axis=bn_axis)
    netD = Discriminator(sync_bn_axis=bn_axis)

    z0 = jnp.zeros((local_b, 1, 1, args.nz))
    gvars = netG.init(jax.random.PRNGKey(0), z0)
    img0 = netG.apply(gvars, z0, train=False)
    dvars = netD.init(jax.random.PRNGKey(1), img0)

    optG = FusedAdam(args.lr, betas=(args.beta1, 0.999))
    optD = FusedAdam(args.lr, betas=(args.beta1, 0.999))
    gp, _, amp_state = amp.initialize(
        gvars["params"], opt_level=args.opt_level, num_losses=3
    )
    dp_params, _, _ = amp.initialize(
        dvars["params"], opt_level=args.opt_level, verbosity=0
    )
    g_bs, d_bs = gvars["batch_stats"], dvars["batch_stats"]
    og, od = optG.init(gp), optD.init(dp_params)
    sstates = amp_state.scaler_states

    def local_step(gp, dp_params, g_bs, d_bs, og, od, sstates, z, z2, real):
        st = amp_state.replace(scaler_states=sstates)

        # --- D step: real + fake (losses 0 and 1, separate scalers,
        # reference main_amp.py scale_loss(..., loss_id))
        def d_loss(dparams):
            fake, g_mut = netG.apply(
                {"params": gp, "batch_stats": g_bs}, z, mutable=["batch_stats"]
            )
            out_real, d_mut = netD.apply(
                {"params": dparams, "batch_stats": d_bs}, real,
                mutable=["batch_stats"],
            )
            out_fake, d_mut2 = netD.apply(
                {"params": dparams, "batch_stats": d_mut["batch_stats"]},
                jax.lax.stop_gradient(fake), mutable=["batch_stats"],
            )
            errD = bce_logits(out_real, jnp.ones_like(out_real)) + bce_logits(
                out_fake, jnp.zeros_like(out_fake)
            )
            return amp.scale_loss(errD, st, 0), (
                g_mut["batch_stats"], d_mut2["batch_stats"], errD
            )

        (_, (g_bs, d_bs, errD)), dgrads = jax.value_and_grad(
            d_loss, has_aux=True
        )(dp_params)
        dgrads = sync_gradients(dgrads, "data")
        dgrads, inf_d = amp.unscale_grads(dgrads, st, 0)
        st, skip_d = amp.update_scale(st, inf_d, 0)
        du, od2 = optD.update(dgrads, od, dp_params)
        dp2 = optax.apply_updates(dp_params, du)
        dp_params = amp.skip_step(skip_d, dp2, dp_params)
        od = amp.skip_step(skip_d, od2, od)

        # --- G step (loss 2)
        def g_loss(gparams):
            fake, g_mut = netG.apply(
                {"params": gparams, "batch_stats": g_bs}, z2,
                mutable=["batch_stats"],
            )
            out, _ = netD.apply(
                {"params": dp_params, "batch_stats": d_bs}, fake,
                mutable=["batch_stats"],
            )
            errG = bce_logits(out, jnp.ones_like(out))
            return amp.scale_loss(errG, st, 2), (g_mut["batch_stats"], errG)

        (_, (g_bs, errG)), ggrads = jax.value_and_grad(g_loss, has_aux=True)(
            gp
        )
        ggrads = sync_gradients(ggrads, "data")
        ggrads, inf_g = amp.unscale_grads(ggrads, st, 2)
        st, skip_g = amp.update_scale(st, inf_g, 2)
        gu, og2 = optG.update(ggrads, og, gp)
        gp2 = optax.apply_updates(gp, gu)
        gp = amp.skip_step(skip_g, gp2, gp)
        og = amp.skip_step(skip_g, og2, og)

        return gp, dp_params, g_bs, d_bs, og, od, st.scaler_states, errD, errG

    step = jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), P(), P(),
                      P("data"), P("data"), P("data")),
            out_specs=(P(), P(), P(), P(), P(), P(), P(), P(), P()),
            check_rep=False,
        )
    )

    rng = jax.random.PRNGKey(2)
    t0 = time.perf_counter()
    for i in range(args.steps):
        rng, k1, k2, k3 = jax.random.split(rng, 4)
        z = jax.random.normal(k1, (args.batch_size, 1, 1, args.nz))
        z2 = jax.random.normal(k2, (args.batch_size, 1, 1, args.nz))
        real = jax.random.uniform(
            k3, (args.batch_size, 64, 64, 3), minval=-1.0, maxval=1.0
        )
        gp, dp_params, g_bs, d_bs, og, od, sstates, errD, errG = step(
            gp, dp_params, g_bs, d_bs, og, od, sstates, z, z2, real
        )
        if (i + 1) % args.print_freq == 0:
            dt = (time.perf_counter() - t0) / args.print_freq
            print(
                f"step {i + 1}: errD {float(errD):.4f} errG {float(errG):.4f}"
                f"  {args.batch_size / dt:.1f} img/s"
            )
            t0 = time.perf_counter()


if __name__ == "__main__":
    main()
