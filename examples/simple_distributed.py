"""Minimal data-parallel training (the reference's simple example).

Reference: examples/simple/distributed/distributed_data_parallel.py —
the ~40-line "hello world" of apex DDP: toy model, DDP wrap, loss,
step. The TPU version: toy model, a mesh, `sync_gradients` inside
`shard_map` — everything else is ordinary JAX.

Run:  python examples/simple_distributed.py
CPU:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
          python examples/simple_distributed.py
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from rocm_apex_tpu.parallel import sync_gradients


def main():
    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    dp = len(devices)

    w = jnp.zeros((10, 1))
    opt = optax.sgd(0.1)
    ostate = opt.init(w)

    def local_step(w, ostate, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(w)
        g = sync_gradients(g, "data")  # the DDP allreduce
        u, ostate2 = opt.update(g, ostate)
        return optax.apply_updates(w, u), ostate2, jax.lax.pmean(loss, "data")

    step = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P()),
            check_rep=False,
        )
    )

    true_w = jnp.linspace(-1, 1, 10)[:, None]
    rng = jax.random.PRNGKey(0)
    for i in range(20):
        rng, k = jax.random.split(rng)
        x = jax.random.normal(k, (8 * dp, 10))
        y = x @ true_w
        w, ostate, loss = step(w, ostate, x, y)
        if (i + 1) % 5 == 0:
            print(f"step {i + 1}: loss {float(loss):.6f}")


if __name__ == "__main__":
    main()
