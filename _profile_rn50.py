"""Dev driver: device-profile the RN50 bench step (fused or unfused)
and print the per-fusion breakdown (the BASELINE.md roofline tables).

Usage: python _profile_rn50.py [fused(0|1)] [iters]
"""

import sys
import tempfile

import jax
import jax.numpy as jnp
import optax

from rocm_apex_tpu import amp, models, profiler
from rocm_apex_tpu.optimizers import FusedAdam

FUSED = bool(int(sys.argv[1])) if len(sys.argv) > 1 else True
ITERS = int(sys.argv[2]) if len(sys.argv) > 2 else 10
BATCH, SIZE = 128, 224


def main():
    model = models.resnet50(
        num_classes=1000, dtype=jnp.bfloat16, fused=FUSED
    )
    x0 = jnp.zeros((BATCH, SIZE, SIZE, 3))
    variables = model.init(jax.random.PRNGKey(0), x0)
    params, batch_stats = variables["params"], variables["batch_stats"]
    optimizer = FusedAdam(1e-3, weight_decay=1e-4)
    params, optimizer, amp_state = amp.initialize(
        params, optimizer, opt_level="O5"
    )
    opt_state = optimizer.init(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, SIZE, SIZE, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (BATCH,), 0, 1000)

    def one_step(carry, _):
        params, batch_stats, opt_state, scaler_states = carry
        st = amp_state.replace(scaler_states=scaler_states)

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats},
                x.astype(jnp.bfloat16),
                mutable=["batch_stats"],
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y
            ).mean()
            return amp.scale_loss(ce, st), (mut["batch_stats"], ce)

        (_, (bs2, ce)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        grads, found_inf = amp.unscale_grads(grads, st)
        st2, skip = amp.update_scale(st, found_inf)
        updates, opt2 = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params = amp.skip_step(skip, new_params, params)
        opt2 = amp.skip_step(skip, opt2, opt_state)
        return (new_params, bs2, opt2, st2.scaler_states), ce

    @jax.jit
    def runN(params, batch_stats, opt_state, scaler_states):
        carry, ces = jax.lax.scan(
            one_step, (params, batch_stats, opt_state, scaler_states),
            None, length=ITERS,
        )
        return carry, ces

    carry, ces = runN(params, batch_stats, opt_state, amp_state.scaler_states)
    float(ces[-1])

    log_dir = tempfile.mkdtemp(prefix="rn50_prof_")
    with profiler.trace(log_dir):
        carry, ces = runN(*carry)
        float(ces[-1])

    stats = profiler.op_stats(log_dir, merge_numeric_suffix=False)
    total = sum(s.total_ms for s in stats if s.name != "while")
    print(f"fused={FUSED} device total (sans while): {total:.1f} ms / "
          f"{ITERS} steps = {total / ITERS:.2f} ms/step")

    import re as _re
    groups = {}
    for s in stats:
        if s.name == "while":
            continue
        kind = _re.sub(r"\.\d+$", "", s.name)
        g = groups.setdefault(kind, [0.0, 0, 0.0])
        g[0] += s.total_ms
        g[1] += s.count
        g[2] = max(g[2], s.tflops_sec)
    print(f"{'ms/step':>8} {'cnt/step':>9} {'tflops':>7}  kind")
    for k, (ms, cnt, tf) in sorted(groups.items(), key=lambda kv: -kv[1][0]):
        if ms / ITERS < 0.05:
            continue
        print(f"{ms / ITERS:8.3f} {cnt / ITERS:9.1f} {tf:7.1f}  {k[:100]}")


if __name__ == "__main__":
    main()
